"""Project-wide registry-drift rules.

Four registries in this tree are held together by free strings that
must stay in sync across files (and with the docs catalogs):

- **fault points** — every ``faultinject.fire("point")`` site must be
  armed by at least one chaos-test arm (with a kind from ``KINDS``)
  and listed in the docs, or the chaos harness silently stops covering
  that path.
- **metric names** — one name, one type, and a docs/observability.md
  catalog entry; a counter re-registered as a gauge elsewhere merges
  into garbage at snapshot-fold time.
- **#control lines** — a literal handled by the server/router with no
  sender (or vice versa) is dead wire protocol; both ends plus the
  docs must agree.
- **config knobs** — raw ``k == "name"`` kwargs reads must name a
  declared ``Param`` field, and every ``DIFACTO_*`` env knob read must
  be documented.

Cross rules see the :class:`core.Project` index (all linted files plus
the docs/tests reference corpora). When the relevant handler/sender
files are not part of the lint set (single-file runs), the two-way
control check degrades to the directions it can still prove.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import (Finding, Project, SourceFile, call_name, dotted,
                   rule, str_const)

# ---------------------------------------------------------------------------
# fault points

_ARM_RE = re.compile(r"([a-z0-9_]+(?:\.[a-z0-9_]+)+):([a-z_]+)[@=]")
_DEFAULT_KINDS = ("err", "truncate", "close", "delay_ms", "kill")


def _fire_sites(project: Project) -> List[Tuple[str, SourceFile, ast.Call]]:
    sites = []
    for sf in project.files:
        if sf.tree is None or sf.rel == project.kinds_file:
            continue
        for node in sf.walk():
            if not isinstance(node, ast.Call):
                continue
            cn = call_name(node)
            if (cn == "fire" or cn.endswith(".fire")) and node.args:
                point = str_const(node.args[0])
                if point:
                    sites.append((point, sf, node))
            for kw in node.keywords:
                if kw.arg == "fault_point":
                    point = str_const(kw.value)
                    if point:
                        sites.append((point, sf, node))
    return sites


def _declared_kinds(project: Project) -> Tuple[str, ...]:
    p = project.root / project.kinds_file
    if not p.exists():
        return _DEFAULT_KINDS
    try:
        tree = ast.parse(p.read_text(encoding="utf-8"))
    except SyntaxError:
        return _DEFAULT_KINDS
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) \
                and any(isinstance(t, ast.Name) and t.id == "KINDS"
                        for t in node.targets) \
                and isinstance(node.value, (ast.Tuple, ast.List)):
            kinds = tuple(str_const(e) for e in node.value.elts)
            if all(kinds):
                return kinds
    return _DEFAULT_KINDS


@rule("fault-registry",
      "every fault point needs a KINDS-valid chaos-test arm and a "
      "docs entry", cross=True)
def check_fault_registry(project: Project) -> List[Finding]:
    out = []
    tests = project.tests_text()
    docs = project.docs_text()
    kinds = set(_declared_kinds(project))
    armed_kinds: Dict[str, Set[str]] = {}
    for point, kind in _ARM_RE.findall(tests):
        armed_kinds.setdefault(point, set()).add(kind)
    seen: Set[str] = set()
    for point, sf, node in _fire_sites(project):
        if point in seen:
            continue
        seen.add(point)
        if point not in tests:
            out.append(sf.finding(
                "fault-registry", node,
                f"fault point \"{point}\" is never armed by the test "
                f"suite — add a chaos-test arm (DIFACTO_FAULTS="
                f"\"{point}:<kind>@1\") so the failure path stays "
                f"covered"))
        else:
            bad = armed_kinds.get(point, set()) - kinds
            if bad:
                out.append(sf.finding(
                    "fault-registry", node,
                    f"tests arm fault point \"{point}\" with unknown "
                    f"kind(s) {sorted(bad)} — KINDS is "
                    f"{sorted(kinds)}; the arm silently never fires"))
        if point not in docs:
            out.append(sf.finding(
                "fault-registry", node,
                f"fault point \"{point}\" is undocumented — add it to "
                f"the docs fault-point catalog (docs/serving.md)"))
    return out


# ---------------------------------------------------------------------------
# metric names

_METRIC_FNS = ("counter", "gauge", "histogram")


@rule("metric-registry",
      "one metric name, one type, one docs catalog entry", cross=True)
def check_metric_registry(project: Project) -> List[Finding]:
    out = []
    doc_path = project.root / project.metrics_doc
    doc_text = doc_path.read_text(encoding="utf-8", errors="replace") \
        if doc_path.exists() else ""
    first: Dict[str, Tuple[str, SourceFile, ast.Call]] = {}
    for sf in project.files:
        if sf.tree is None or sf.rel in project.metrics_impl_files:
            continue
        for node in sf.walk():
            if not isinstance(node, ast.Call) or not node.args:
                continue
            cn = call_name(node)
            kind = cn.rsplit(".", 1)[-1]
            if kind not in _METRIC_FNS:
                continue
            name = str_const(node.args[0])
            if not name or not re.fullmatch(r"[a-z][a-z0-9_]+", name):
                continue
            if name in first:
                k0, sf0, n0 = first[name]
                if kind != k0:
                    out.append(sf.finding(
                        "metric-registry", node,
                        f"metric \"{name}\" registered as {kind} here "
                        f"but as {k0} at {sf0.rel}:{n0.lineno} — one "
                        f"name must keep one type or snapshot folds "
                        f"merge garbage"))
                continue
            first[name] = (kind, sf, node)
            if name not in doc_text:
                out.append(sf.finding(
                    "metric-registry", node,
                    f"metric \"{name}\" ({kind}) is missing from the "
                    f"{project.metrics_doc} catalog — document it or "
                    f"it drifts unnamed"))
    return out


# ---------------------------------------------------------------------------
# control lines

_CTRL_RE = re.compile(r"#[a-z][a-z_]*\Z")


def _control_literals(files: List[SourceFile]) \
        -> Dict[str, Tuple[SourceFile, ast.Constant]]:
    out: Dict[str, Tuple[SourceFile, ast.Constant]] = {}
    for sf in files:
        if sf.tree is None:
            continue
        for node in sf.walk():
            s = str_const(node)
            if s is None:
                continue
            s = s.strip()
            if _CTRL_RE.fullmatch(s) and s not in out:
                out[s] = (sf, node)
    return out


@rule("control-registry",
      "#control lines need both a handler and a sender (and a docs "
      "entry)", cross=True)
def check_control_registry(project: Project) -> List[Finding]:
    handlers = _control_literals(project.match_files(project.handler_files))
    senders = _control_literals(project.match_files(project.sender_files))
    docs = project.docs_text()
    out = []
    if senders:
        for line, (sf, node) in sorted(handlers.items()):
            if line not in senders:
                out.append(sf.finding(
                    "control-registry", node,
                    f"control line \"{line}\" is handled here but no "
                    f"client/fleet/tool ever sends it — dead protocol "
                    f"or a missing sender"))
    if handlers:
        for line, (sf, node) in sorted(senders.items()):
            if line not in handlers:
                out.append(sf.finding(
                    "control-registry", node,
                    f"control line \"{line}\" is sent here but the "
                    f"server/router never handles it — the peer will "
                    f"parse it as a data row"))
    for line, (sf, node) in sorted(handlers.items()):
        if line not in docs:
            out.append(sf.finding(
                "control-registry", node,
                f"control line \"{line}\" is undocumented — add it to "
                f"the docs wire-protocol catalog"))
    return out


# ---------------------------------------------------------------------------
# config knobs

_ENV_RE = re.compile(r"DIFACTO_[A-Z][A-Z0-9_]*\Z")


def _declared_params(project: Project) -> Set[str]:
    names: Set[str] = set()
    for sf in project.files:
        if sf.tree is None:
            continue
        for node in sf.walk():
            if not isinstance(node, ast.ClassDef):
                continue
            if not any(dotted(b).split(".")[-1].endswith("Param")
                       for b in node.bases):
                continue
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) \
                        and isinstance(stmt.target, ast.Name):
                    names.add(stmt.target.id)
                elif isinstance(stmt, ast.Assign):
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            names.add(t.id)
    return names


def _kwargs_read_keys(sf: SourceFile) -> List[Tuple[str, ast.Compare]]:
    """Literal keys compared against the key half of a ``for k, v in
    <kwargs-ish>`` iteration — the raw config-read pattern."""
    reads = []
    loops = []
    for node in sf.walk():
        if isinstance(node, (ast.For, ast.comprehension)):
            tgt, it = node.target, node.iter
            if isinstance(tgt, ast.Tuple) and tgt.elts \
                    and isinstance(tgt.elts[0], ast.Name):
                names_in_iter = {n.id for n in ast.walk(it)
                                 if isinstance(n, ast.Name)}
                if names_in_iter & {"kwargs", "remain", "kv", "args_kv"}:
                    body = node.body if isinstance(node, ast.For) else \
                        list(node.ifs)
                    parent = node if isinstance(node, ast.For) else \
                        getattr(node, "parent", None)
                    loops.append((tgt.elts[0].id, parent or node, body))
    for keyname, scope_node, body in loops:
        stmts = body or [scope_node]
        for stmt in stmts:
            for n in ast.walk(stmt):
                if not isinstance(n, ast.Compare) or len(n.ops) != 1 \
                        or not isinstance(n.ops[0], (ast.Eq, ast.NotEq)):
                    continue
                left, right = n.left, n.comparators[0]
                if isinstance(left, ast.Name) and left.id == keyname:
                    lit = str_const(right)
                    if lit:
                        reads.append((lit, n))
    return reads


def _env_reads(sf: SourceFile) -> List[Tuple[str, ast.AST]]:
    out = []
    for node in sf.walk():
        name: Optional[str] = None
        if isinstance(node, ast.Call):
            cn = call_name(node)
            if cn in ("os.environ.get", "environ.get", "os.getenv",
                      "getenv") and node.args:
                name = str_const(node.args[0])
        elif isinstance(node, ast.Subscript):
            if dotted(node.value) in ("os.environ", "environ"):
                name = str_const(node.slice)
        if name and _ENV_RE.fullmatch(name):
            out.append((name, node))
    return out


@rule("config-registry",
      "raw config reads must name declared Param fields; DIFACTO_* env "
      "knobs must be documented", cross=True)
def check_config_registry(project: Project) -> List[Finding]:
    declared = _declared_params(project)
    docs = project.docs_text()
    out = []
    seen_env: Set[str] = set()
    for sf in project.files:
        if sf.tree is None:
            continue
        for key, node in _kwargs_read_keys(sf):
            if declared and key not in declared:
                out.append(sf.finding(
                    "config-registry", node,
                    f"raw kwargs read of \"{key}\" but no Param "
                    f"subclass declares that field — the knob is "
                    f"invisible to the config chain (and to "
                    f"warn_unknown)"))
        for name, node in _env_reads(sf):
            if name in seen_env:
                continue
            seen_env.add(name)
            if name not in docs:
                out.append(sf.finding(
                    "config-registry", node,
                    f"env knob {name} is read here but documented "
                    f"nowhere in docs/ or README — add it to the "
                    f"environment-knob catalog"))
    return out
