"""Eraser-style static data-race detection (difacto-lint v3).

The concurrency layer (v2) proves locks are taken in a consistent
*order*; this pass answers the other half: which shared fields each
lock actually *guards*, and which are touched by two threads with no
common lock at all. Three stages, all riding the call graph and the
single held-set walk the concurrency model already does:

1. **Thread-root discovery** — every concurrent entry point: the main
   thread (``<main>``: all module-level code and what it reaches), and
   every ``Thread``/``Process`` target, executor ``submit``/``map``
   callable, or producer/serve worker the callgraph resolves (including
   ``functools.partial``, ``lambda``, bound-method and local-alias
   forms). A root spawned from inside a loop, or from two different
   sites, is *multi-instance*: it can race with itself. Reachability
   over call edges (thread edges start a NEW root, they do not extend
   the spawner's) gives each function its set of reaching roots.

2. **Shared-state index** — every mutable location with an identity the
   lock model already uses: ``self.attr`` / ``cls.attr`` class
   attributes (``rel.py::Class.attr``, unified across a class and the
   base that first writes the attribute), module globals written under
   a ``global`` declaration (``rel.py::name``), and closure cells a
   nested function shares with its binder (``rel.py::func.var``). Each
   read/write site carries its reaching roots and its *effective
   lockset*: the locks held at the site plus the locks held at every
   call site leading there (the entry lockset — the intersection over
   all callers, so a helper only "inherits" a lock every caller takes).

3. **Lockset inference** — per field, Eraser's rule: intersect the
   effective locksets of all post-init accesses. A non-empty
   intersection is an inferred ``GuardedBy`` fact (folded into ``make
   lockmap``). An EMPTY intersection on a field reachable from >= 2
   roots with at least one write is a ``data-race`` finding, reported
   with a two-site witness: the conflicting write and read/write, each
   side's roots and held locks.

Escape hatches that keep false positives sane (docs/static_analysis.md
v3 lists the full catalog):

- **init-before-publish** — accesses inside ``__init__`` are
  construction, before the object can be visible to another thread;
  closure-cell accesses in the binder *above its first thread spawn*
  are likewise setup;
- **immutable-after-publish** — a field never written outside init
  (config, wired callbacks, lock objects themselves) cannot race;
- locks, ``Condition``/queue objects and dunders are excluded; deep
  mutation (``self.d[k] = v`` mutates the dict, not the binding) is a
  documented blind spot — the binding read still indexes the field.

The runtime complement is ``utils/shared.py`` (``DIFACTO_RACETRACE=1``)
whose observed (field, thread, locks-held) tuples the tier-1 gate
checks against this model: every dynamically multi-thread field must be
statically guarded or carry a reasoned ``# lint: ok(data-race)``.
"""

from __future__ import annotations

import ast
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import CallGraph
from .concurrency import ConcurrencyModel, _short, get_model
from .core import Finding, Project, rule

MAIN_ROOT = "<main>"

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)


@dataclass
class Access:
    field_id: str
    path: str
    line: int
    func: str                  # owning function qual
    write: bool
    init: bool                 # construction access (escape hatch)
    locks: Tuple[str, ...]     # effective lockset at the site


@dataclass
class FieldInfo:
    field_id: str
    kind: str                  # "attr" | "global" | "cell"
    path: str
    accesses: List[Access] = field(default_factory=list)
    roots: Set[str] = field(default_factory=set)
    weight: int = 0            # multiplicity-weighted root count
    guard: Tuple[str, ...] = ()


def _root_name(root: str) -> str:
    return root if root == MAIN_ROOT else _short(root)


class RaceModel:
    """The whole-program shared-state model. Built once per Project
    (cached — the data-race rule, lockmap, and the tier-1 gate share
    it) on top of the cached ConcurrencyModel: no extra tree walk."""

    def __init__(self, project: Project):
        self.project = project
        self.cm: ConcurrencyModel = get_model(project)
        self.cg: CallGraph = self.cm.cg
        self.roots: Dict[str, int] = {}           # root -> multiplicity
        self.func_roots: Dict[str, Set[str]] = {}
        self.entry_locks: Dict[str, frozenset] = {}
        self.fields: Dict[str, FieldInfo] = {}
        self.guarded_by: Dict[str, Tuple[str, ...]] = {}
        self.readonly: Set[str] = set()
        self.suppressed_fields: Set[str] = set()
        self._findings: List[Finding] = []
        self._discover_roots()
        self._compute_entry_locks()
        self._index_accesses()
        self._infer()

    # ------------------------------------------------------ thread roots
    @staticmethod
    def _in_loop(node) -> bool:
        cur = getattr(node, "parent", None)
        while cur is not None and not isinstance(cur, _FUNC_DEFS):
            if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
                return True
            cur = getattr(cur, "parent", None)
        return False

    def _discover_roots(self) -> None:
        spawns: Dict[str, List[ast.Call]] = {}
        for sites in self.cg.calls.values():
            for site in sites:
                if site.kind != "thread":
                    continue
                for t in site.targets:
                    spawns.setdefault(t, []).append(site.node)
        self.roots[MAIN_ROOT] = 1
        for t, nodes in sorted(spawns.items()):
            # spawned in a loop or from several sites: the root can run
            # as two concurrent instances and race with itself
            multi = len(nodes) > 1 or any(self._in_loop(n) for n in nodes)
            self.roots[t] = 2 if multi else 1

        # reachability over EXACT call edges only: the multi-candidate
        # attribute heuristic (CallSite.fuzzy) is a safe superset for
        # lock ordering, but here it would smear serve-thread roots
        # into every class with a same-named method (every learner's
        # `load` would look reload-thread-reachable)
        adj: Dict[str, List[str]] = {}
        for qual, sites in self.cg.calls.items():
            outs: Set[str] = set()
            for site in sites:
                if site.kind == "call" and not site.fuzzy:
                    outs.update(site.targets)
            adj[qual] = sorted(outs)
        self.func_roots = {q: set() for q in self.cg.funcs}
        for root in sorted(self.roots):
            seeds = [q for q in self.cg.funcs
                     if q.endswith("::<module>")] \
                if root == MAIN_ROOT else [root]
            seen = {s for s in seeds if s in self.cg.funcs}
            frontier = list(seen)
            while frontier:
                q = frontier.pop()
                self.func_roots.setdefault(q, set()).add(root)
                for t in adj.get(q, []):
                    if t not in seen and t in self.cg.funcs:
                        seen.add(t)
                        frontier.append(t)

    def root_weight(self, roots: Set[str]) -> int:
        return sum(self.roots.get(r, 1) for r in roots)

    # ----------------------------------------------------- entry locksets
    def _compute_entry_locks(self) -> None:
        """entry_locks[f]: locks held at EVERY resolved call into f
        (meet over callers; roots and module bodies start empty). The
        effective lockset at an access is entry ∪ locally-held."""
        facts = self.cm.facts
        site_held: Dict[int, Tuple[str, ...]] = {}
        for f in facts.values():
            for held, call in f.call_events:
                site_held[id(call)] = tuple(lk for lk, _ in held)
        entry: Dict[str, Optional[frozenset]] = {q: None for q in facts}
        forced = set()
        for q in facts:
            if q.endswith("::<module>") or q in self.roots:
                entry[q] = frozenset()
                forced.add(q)
        work = deque(sorted(forced))
        inwork = set(work)
        while work:
            q = work.popleft()
            inwork.discard(q)
            eq = entry[q]
            if eq is None:
                continue
            for site in self.cg.calls.get(q, []):
                if site.kind != "call" or site.fuzzy:
                    # fuzzy edges would let a spurious lock-free caller
                    # empty a helper's entry lockset — exact edges only,
                    # symmetric with root reachability
                    continue
                contrib = eq | frozenset(
                    site_held.get(id(site.node), ()))
                for t in site.targets:
                    if t not in entry or t in forced or t == q:
                        continue
                    cur = entry[t]
                    new = contrib if cur is None else (cur & contrib)
                    if new != cur:
                        entry[t] = new
                        if t not in inwork:
                            work.append(t)
                            inwork.add(t)
        self.entry_locks = {q: (e if e is not None else frozenset())
                            for q, e in entry.items()}

    # ------------------------------------------------------ access index
    def _attr_owner(self, ci, attr: str, depth: int = 0):
        """The class that owns an attribute: the deepest base that
        writes it (so one field unifies across a base and its
        subclasses), else the accessing class itself."""
        if depth > 4 or ci is None:
            return None
        for base in ci.bases:
            for bi in self.cg.classes.get(base, []):
                got = self._attr_owner(bi, attr, depth + 1)
                if got is not None:
                    return got
        if attr in self._attrs_written.get(ci.qual, set()):
            return ci
        return None

    def _index_accesses(self) -> None:
        facts = self.cm.facts
        # pass 1: which classes write which attrs (ownership unification)
        self._attrs_written: Dict[str, Set[str]] = {}
        for qual, f in facts.items():
            fi = self.cg.funcs.get(qual)
            if fi is None or fi.cls is None:
                continue
            for _held, node in f.access_events:
                if isinstance(node, ast.Attribute) \
                        and isinstance(node.ctx, (ast.Store, ast.Del)):
                    self._attrs_written.setdefault(
                        fi.cls.qual, set()).add(node.attr)
        # per-file name classification corpora
        mod_locals: Dict[str, Set[str]] = {}
        file_global_decls: Dict[str, Set[str]] = {}
        for qual, f in facts.items():
            rel = f.sf.rel
            if qual.endswith("::<module>"):
                mod_locals[rel] = f.local_names
            file_global_decls.setdefault(rel, set()).update(
                f.global_names)
        # first thread-spawn / last join line per function (the cell
        # happens-before hatches: binder accesses BEFORE the spawn are
        # construction, binder accesses AFTER the last `t.join()` are
        # sequenced after every thread the frame owns)
        spawn_line: Dict[str, int] = {}
        join_line: Dict[str, int] = {}
        for qual, sites in self.cg.calls.items():
            lines = [s.node.lineno for s in sites if s.kind == "thread"]
            if lines:
                spawn_line[qual] = min(lines)
            joins = [s.node.lineno for s in sites
                     if isinstance(s.node.func, ast.Attribute)
                     and s.node.func.attr == "join"
                     # 0-arg join() is Thread/Process; str.join and a
                     # timeout-bounded join (may return early) are not
                     # a happens-before edge
                     and not s.node.args and not s.node.keywords]
            if joins:
                join_line[qual] = max(joins)

        lock_ids = set(self.cm.locks)
        for qual in sorted(facts):
            f = facts[qual]
            fi = self.cg.funcs.get(qual)
            entry = self.entry_locks.get(qual, frozenset())
            for held, node in f.access_events:
                rec: Optional[Tuple[str, str, bool, bool]] = None
                if isinstance(node, ast.Attribute):
                    if fi is None or fi.cls is None:
                        continue
                    owner = self._attr_owner(fi.cls, node.attr) or fi.cls
                    fid = f"{owner.sf.rel}::{owner.name}.{node.attr}"
                    write = isinstance(node.ctx, (ast.Store, ast.Del))
                    rec = (fid, "attr", write, fi.name == "__init__")
                elif isinstance(node, ast.Name):
                    rec = self._classify_name(
                        qual, f, node, mod_locals, file_global_decls,
                        spawn_line, join_line)
                if rec is None:
                    continue
                fid, kind, write, init = rec
                if fid in lock_ids:
                    continue
                info = self.fields.get(fid)
                if info is None:
                    info = self.fields[fid] = FieldInfo(
                        fid, kind, fid.partition("::")[0])
                info.accesses.append(Access(
                    fid, f.sf.rel, getattr(node, "lineno", 0), qual,
                    write, init,
                    tuple(sorted(entry | set(held)))))

    def _classify_name(self, qual, f, node, mod_locals,
                       file_global_decls, spawn_line, join_line):
        nid = node.id
        rel = f.sf.rel
        write = isinstance(node.ctx, (ast.Store, ast.Del))
        if qual.endswith("::<module>"):
            # module body: every binding there is a global, and the
            # body runs at import, before any thread exists — writes
            # are init-before-publish by construction
            if nid in f.local_names or nid in f.cell_names:
                return (f"{rel}::{nid}", "global", write, True)
            return None
        if nid in f.cell_names:
            # the binder's own access to a cell var: construction until
            # the first thread spawn in this function publishes it, and
            # sequenced again after the frame's last `t.join()` (the
            # loadgen pattern — workers write counters, the binder reads
            # them only after joining every worker)
            init = node.lineno < spawn_line.get(qual, 0) \
                or (qual in join_line
                    and node.lineno > join_line[qual])
            return (f"{qual}.{nid}", "cell", write, init)
        if nid in f.global_names:
            return (f"{rel}::{nid}", "global", write, False)
        # nonlocal / free variable: find the binding enclosing function
        prefix, _, _name = qual.rpartition(".")
        while "::" in prefix:
            outer = self.cm.facts.get(prefix)
            if outer is not None and nid in outer.cell_names:
                return (f"{prefix}.{nid}", "cell", write, False)
            prefix = prefix.rpartition(".")[0]
        if nid in mod_locals.get(rel, set()) \
                or nid in file_global_decls.get(rel, set()):
            if not write:
                # module-global read; writes only count under a
                # `global` declaration (handled above) — a Store here
                # is a local the scanner classified, not this field
                return (f"{rel}::{nid}", "global", False, False)
        return None

    # ---------------------------------------------------------- inference
    def _access_roots(self, a: Access) -> Set[str]:
        """Roots reaching an access. A function NO root reaches (dead
        to the static graph — e.g. a ``close()`` only tests call, or a
        callback behind ``getattr`` dispatch) is attributed to the main
        root: its accesses still conflict with worker-thread accesses,
        and dropping them would silently shrink the race surface."""
        return self.func_roots.get(a.func) or {MAIN_ROOT}

    def _cell_is_shared(self, info: FieldInfo) -> bool:
        """A closure cell lives per CALL FRAME of its binder: it is
        shared between threads only when the binder hands a nested
        function to another thread (``Thread(target=inner)`` /
        ``submit(inner)``). Without a spawned nested accessor the cell
        is thread-confined however many roots reach the binder."""
        binder = info.field_id.rsplit(".", 1)[0]
        spawned = {
            t
            for site in self.cg.calls.get(binder, [])
            if site.kind == "thread"
            for t in site.targets
            if t.startswith(binder + ".")
        }
        if not spawned:
            return False
        return any(a.func in spawned
                   or any(a.func.startswith(t + ".") for t in spawned)
                   for a in info.accesses)

    def _infer(self) -> None:
        for fid in sorted(self.fields):
            info = self.fields[fid]
            if info.kind == "cell" and not self._cell_is_shared(info):
                continue                    # per-call frame, not shared
            non_init = [a for a in info.accesses if not a.init]
            writes = [a for a in non_init if a.write]
            guard: Optional[Set[str]] = None
            for a in non_init:
                s = set(a.locks)
                guard = s if guard is None else guard & s
            if guard:
                # consistently locked on every post-init access —
                # recorded whatever the root weight, so the RACETRACE
                # gate recognizes the field even when the static root
                # count underestimates (e.g. a single-root helper)
                info.guard = tuple(sorted(guard))
            if not writes:
                self.readonly.add(fid)      # immutable-after-publish
                continue
            roots: Set[str] = set()
            for a in non_init:
                roots |= self._access_roots(a)
            info.roots = roots
            info.weight = self.root_weight(roots)
            if info.weight < 2:
                continue                    # single-threaded
            if info.guard:
                self.guarded_by[fid] = info.guard
                continue
            self._findings.append(
                self._race_finding(info, writes, non_init))

    def _race_finding(self, info: FieldInfo, writes: List[Access],
                      non_init: List[Access]) -> Finding:
        by_site = sorted(non_init, key=lambda a: (a.path, a.line))
        # the best witness pair EXPLAINS the empty lockset: a write and
        # another access holding no lock in common, from different
        # roots when one exists
        best: Optional[Tuple[Access, Access]] = None
        best_score = (-1, -1)
        for w in sorted(writes, key=lambda a: (a.path, a.line)):
            w_roots = self._access_roots(w)
            w_locks = set(w.locks)
            for cand in by_site:
                if cand is w:
                    continue
                score = (1 if not (w_locks & set(cand.locks)) else 0,
                         1 if self._access_roots(cand) - w_roots else 0)
                if score > best_score:
                    best_score = score
                    best = (w, cand)
        if best is None:
            w = writes[0]
            other = w                   # one-site field (e.g. `x += 1`)
        else:
            w, other = best

        def side(a: Access) -> str:
            kind = "write" if a.write else "read"
            roots = ", ".join(sorted(
                _root_name(r) for r in self._access_roots(a)))
            locks = ", ".join(_short(lk) for lk in a.locks) or "none"
            fn = a.func.split("::", 1)[1]
            return (f"{kind} at {a.path}:{a.line} in {fn} "
                    f"[roots: {roots}; locks: {locks}]")

        witness = side(w) if other is w \
            else f"{side(w)} vs {side(other)}"
        msg = (f"data-race on {_short(info.field_id)}: {witness} — no "
               f"common lock guards this multi-root field (Eraser "
               f"lockset is empty); guard every access with one lock, "
               f"or annotate a witness line with "
               f"`# lint: ok(data-race) <why this is safe>`")
        # anchor at a pragma-carrying access site when one exists, so
        # one reasoned annotation anywhere on the field silences it
        anchor = w
        by_rel = {sf.rel: sf for sf in self.project.files}
        for a in sorted(info.accesses, key=lambda a: (a.path, a.line)):
            sf = by_rel.get(a.path)
            if sf is not None and "data-race" in sf.suppressions.get(
                    a.line, set()):
                anchor = a
                self.suppressed_fields.add(info.field_id)
                break
        sf = by_rel.get(anchor.path)
        snippet = sf.line_text(anchor.line) if sf is not None else ""
        return Finding("data-race", anchor.path, anchor.line, msg,
                       snippet=snippet)

    # ------------------------------------------------------------ outputs
    def race_findings(self) -> List[Finding]:
        return list(self._findings)

    def known_safe(self) -> Set[str]:
        """Fields the tier-1 RACETRACE gate accepts as multi-thread:
        consistently locked on every post-init access (the multi-root
        subset of these are the GuardedBy facts), read-only after
        publish, or suppressed with a reasoned pragma."""
        locked = {fid for fid, info in self.fields.items() if info.guard}
        return locked | self.readonly | self.suppressed_fields

    def to_json(self) -> dict:
        return {
            "thread_roots": {r: m for r, m in sorted(self.roots.items())},
            "guarded_by": {fid: list(locks)
                           for fid, locks in sorted(
                               self.guarded_by.items())},
            "fields": {
                fid: {
                    "kind": info.kind,
                    "accesses": len(info.accesses),
                    "writes": sum(a.write for a in info.accesses
                                  if not a.init),
                    "roots": sorted(info.roots),
                    "weight": info.weight,
                    "guard": list(info.guard),
                }
                for fid, info in sorted(self.fields.items())
                if info.weight >= 2
            },
        }


def get_race_model(project: Project) -> RaceModel:
    m = getattr(project, "_race_model", None)
    if m is None or m.project is not project:
        m = RaceModel(project)
        project._race_model = m  # type: ignore[attr-defined]
    return m


@rule("data-race",
      "multi-thread shared state must keep a non-empty common lockset",
      cross=True)
def check_data_race(project: Project) -> List[Finding]:
    return get_race_model(project).race_findings()
