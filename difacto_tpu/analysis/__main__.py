"""``python -m difacto_tpu.analysis`` -> the difacto-lint CLI."""

import sys

from .cli import main

sys.exit(main())
