"""difacto_tpu — a TPU-native distributed factorization-machine framework.

A from-scratch JAX/XLA re-design of the capabilities of DiFacto (distributed
FM / l1-regularized logistic regression, parameter-server architecture):
the server-side sparse model becomes a mesh-sharded slot table, the
pull/compute/push round-trip becomes one fused jit step (gather -> segment-sum
forward/backward -> scatter FTRL/AdaGrad update), and worker data parallelism
becomes batch sharding over the mesh data axis.
"""

__version__ = "0.1.0"
