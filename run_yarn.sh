#!/bin/bash
# YARN cluster run (reference run_yarn.sh equivalent, which submitted 50
# workers + 50 servers through dmlc-tracker): here the yarn
# distributed-shell client starts N rankless containers; each runs the
# launch.py shim, claims a rank through the shared rendezvous dir, and
# joins the SPMD rendezvous (rank 0 = coordinator). The rendezvous dir
# must be on a filesystem every container mounts.
python launch.py --launcher yarn -n 8 \
    --rendezvous-dir /shared/difacto_rdv \
    -- python -m difacto_tpu examples/local.conf "$@"
