#!/bin/bash
# single-process local run (reference run_local.sh equivalent)
python -m difacto_tpu examples/local.conf "$@"
