#!/usr/bin/env python
"""Render a human summary from obs artifacts (ISSUE 4 tooling).

Inputs are what the observability subsystem writes during a run:

- a metrics JSONL event log (``metrics_path`` training knob, or any
  file of ``{"ts", "metrics"}`` lines from obs/export.MetricsFlusher) —
  the LAST line is the run's final cumulative snapshot;
- optionally a Chrome trace JSON (``DIFACTO_TRACE=<path>``).

Output: the streamed-stage table (where the run's seconds went), every
histogram's count/mean/p50/p95/p99, top counters, and the top span
names by total duration — the first thing to read when a streamed rate
regresses or a serve replica's latency moves.

    python tools/obs_report.py --metrics run.metrics.jsonl \
        --trace run.trace.json
    make obs-report METRICS=run.metrics.jsonl TRACE=run.trace.json
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict

sys.path.insert(0, __file__.rsplit("/", 2)[0])  # repo root

STAGE_ORDER = ("parse", "pack", "ring_wait", "transfer", "step")


def load_last_snapshot(path: str) -> dict:
    """Last parseable line of the JSONL log (a torn final line — crash
    mid-flush — is skipped, the previous flush wins). Reads the rolled
    file ``<path>.1`` first when present (MetricsFlusher ``max_mb``
    rotation): snapshots are cumulative, so the newest line across both
    files — the live file's, unless it is fresh-empty right after a
    roll — is the run's state."""
    import os
    last = None
    for p in (path + ".1", path):
        if not os.path.exists(p):
            continue
        with open(p) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    last = json.loads(line)
                except ValueError:
                    continue
    if last is None:
        raise SystemExit(f"no parseable JSONL lines in {path}"
                         f" (or {path}.1)")
    return last.get("metrics", last)


def fmt_seconds(v: float) -> str:
    if v >= 1.0:
        return f"{v:8.2f}s"
    return f"{v * 1e3:7.2f}ms"


def report_stages(snap: dict) -> None:
    series = snap.get("counters", {}).get("stage_seconds_total", {})
    if not series:
        return
    vals = {}
    for key, v in series.items():
        # flattened label key: "stage=pack" (export.jsonable_snapshot)
        stage = dict(p.split("=", 1) for p in key.split(",")
                     if "=" in p).get("stage", key)
        vals[stage] = vals.get(stage, 0.0) + v
    total = sum(vals.values()) or 1.0
    print("== streamed stage table (seconds, % of accounted time) ==")
    for stage in STAGE_ORDER + tuple(sorted(set(vals) - set(STAGE_ORDER))):
        if stage in vals:
            v = vals[stage]
            print(f"  {stage:10s} {v:10.3f}s  {100 * v / total:5.1f}%")
    print()


def _quantiles(d: dict, qs=(0.5, 0.95, 0.99)) -> dict:
    from difacto_tpu.obs import hist_quantiles
    return hist_quantiles(d, qs)


def report_hists(snap: dict) -> None:
    hists = snap.get("hists", {})
    if not hists:
        return
    print("== histograms (count / mean / p50 / p95 / p99) ==")
    for name in sorted(hists):
        for key, d in sorted(hists[name].items()):
            label = f"{name}{{{key}}}" if key else name
            n = d.get("count", 0)
            if not n:
                continue
            q = _quantiles(d)
            mean = d.get("sum", 0.0) / n
            print(f"  {label:44s} n={n:<9d} mean={fmt_seconds(mean)} "
                  f"p50={fmt_seconds(q[0.5])} p95={fmt_seconds(q[0.95])} "
                  f"p99={fmt_seconds(q[0.99])}")
    print()


def report_gauges(snap: dict) -> None:
    """Instantaneous state at the final flush — in particular the
    online-loop freshness SLO trio (train_behind_serve_s,
    online_rows_behind, serve_generation_age_s; docs/serving.md
    "Continuous learning")."""
    rows = []
    for name, series in snap.get("gauges", {}).items():
        for key, v in series.items():
            rows.append((f"{name}{{{key}}}" if key else name, v))
    if not rows:
        return
    print("== gauges (at last flush) ==")
    for label, v in sorted(rows):
        print(f"  {label:54s} {v:g}")
    print()


def report_fleet(snap: dict) -> None:
    """Fleet-elasticity digest (docs/observability.md): the autoscaler's
    decisions (``autoscale_*``) and the router group's supervision
    (``router_group_*``) in one block, so a chaos/diurnal run's capacity
    story reads without hunting through the counter table."""
    counters = snap.get("counters", {})
    gauges = snap.get("gauges", {})

    def _total(section, name):
        series = section.get(name)
        if not series:
            return None
        return sum(series.values())

    rows = []
    for name in ("autoscale_spawns_total", "autoscale_drains_total",
                 "autoscale_aborts_total",
                 "router_group_relaunches_total"):
        v = _total(counters, name)
        if v is not None:
            rows.append((name, v))
    for name in ("autoscale_replicas", "router_group_size",
                 "autoscale_queue_frac", "autoscale_shed_rate",
                 "router_affinity_hit_rate"):
        v = _total(gauges, name)
        if v is not None:
            rows.append((name, v))
    if not rows:
        return
    print("== fleet elasticity (autoscaler + router group) ==")
    for label, v in rows:
        print(f"  {label:54s} {v:g}")
    print()


def report_capacity(snap: dict) -> None:
    """Table-capacity digest (docs/observability.md): the cold tier's
    residency traffic (``store_tier_*``), admission drops, occupancy
    eviction and the per-shard occupancy gauges in one block, plus the
    derived tier hit-rate — the first read when judging whether
    ``cold_tier_rows`` / ``admit_min_count`` are sized right for the
    key skew (docs/perf_notes.md "Table capacity")."""
    counters = snap.get("counters", {})
    gauges = snap.get("gauges", {})

    def _total(section, name):
        series = section.get(name)
        if not series:
            return None
        return sum(series.values())

    rows = []
    for name in ("store_tier_hits_total", "store_tier_misses_total",
                 "store_tier_promotes_total", "store_tier_demotes_total",
                 "store_evictions_total", "store_admit_drops_total"):
        v = _total(counters, name)
        if v is not None:
            rows.append((name, v))
    hits = _total(counters, "store_tier_hits_total")
    misses = _total(counters, "store_tier_misses_total")
    if hits is not None and misses is not None and hits + misses > 0:
        rows.append(("tier_hit_rate (derived)", hits / (hits + misses)))
    for name in ("store_shard_rows", "store_shard_occupancy"):
        series = gauges.get(name, {})
        for key, v in sorted(series.items()):
            rows.append((f"{name}{{{key}}}" if key else name, v))
    if not rows:
        return
    print("== table capacity (cold tier + admission + occupancy) ==")
    for label, v in rows:
        print(f"  {label:54s} {v:g}")
    print()


def report_durability(snap: dict) -> None:
    """Durability digest (docs/observability.md): the write-ahead
    delta log's volume and recovery yield (``wal_*``), replica push
    health and per-peer staleness (``replica_*``), and which recovery
    rungs resumes actually climbed (``recovery_rung_total{rung}``) —
    the first read after a chaos run or a real host loss
    (docs/serving.md "Durability & recovery")."""
    counters = snap.get("counters", {})
    gauges = snap.get("gauges", {})

    def _total(section, name):
        series = section.get(name)
        if not series:
            return None
        return sum(series.values())

    rows = []
    for name in ("wal_bytes_total", "wal_append_failures_total",
                 "wal_replay_batches",
                 "replica_push_failures_total",
                 "replica_fetch_failures_total",
                 "replica_scrub_repairs_total"):
        v = _total(counters, name)
        if v is not None:
            rows.append((name, v))
    for name in ("wal_replay_dropped_total", "recovery_rung_total"):
        series = counters.get(name, {})
        for key, v in sorted(series.items()):
            rows.append((f"{name}{{{key}}}" if key else name, v))
    series = gauges.get("replica_lag_generations", {})
    for key, v in sorted(series.items()):
        rows.append((f"replica_lag_generations{{{key}}}" if key
                     else "replica_lag_generations", v))
    if not rows:
        return
    print("== durability (WAL + replicas + recovery ladder) ==")
    for label, v in rows:
        print(f"  {label:54s} {v:g}")
    print()


def report_counters(snap: dict, top: int = 20) -> None:
    rows = []
    for name, series in snap.get("counters", {}).items():
        if name == "stage_seconds_total":
            continue  # already in the stage table
        for key, v in series.items():
            rows.append((v, f"{name}{{{key}}}" if key else name))
    if not rows:
        return
    print(f"== top counters ==")
    for v, label in sorted(rows, reverse=True)[:top]:
        print(f"  {label:54s} {v:g}")
    print()


def report_trace(path: str, top: int = 15) -> None:
    with open(path) as f:
        doc = json.load(f)
    events = doc.get("traceEvents", doc if isinstance(doc, list) else [])
    total = defaultdict(float)
    count = defaultdict(int)
    for ev in events:
        if ev.get("ph") != "X":
            continue
        total[ev["name"]] += ev.get("dur", 0.0)
        count[ev["name"]] += 1
    if not total:
        return
    print(f"== top spans by total duration ({len(events)} events; "
          "open the file in ui.perfetto.dev for the timeline) ==")
    for name, us in sorted(total.items(), key=lambda kv: -kv[1])[:top]:
        print(f"  {name:34s} {us / 1e6:10.3f}s  x{count[name]:<8d} "
              f"avg {fmt_seconds(us / count[name] / 1e6)}")
    print()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--metrics", default="",
                    help="metrics JSONL event log (metrics_path knob)")
    ap.add_argument("--trace", default="",
                    help="Chrome trace JSON (DIFACTO_TRACE)")
    ap.add_argument("--top", type=int, default=20,
                    help="rows per top-N section")
    args = ap.parse_args()
    if not args.metrics and not args.trace:
        ap.error("pass --metrics and/or --trace")
    if args.metrics:
        snap = load_last_snapshot(args.metrics)
        report_stages(snap)
        report_hists(snap)
        report_fleet(snap)
        report_capacity(snap)
        report_durability(snap)
        report_gauges(snap)
        report_counters(snap, args.top)
    if args.trace:
        report_trace(args.trace, args.top)
    return 0


if __name__ == "__main__":
    sys.exit(main())
