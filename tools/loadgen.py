"""Open-loop Poisson load generator for the serving front-end.

Open-loop means arrivals follow a fixed schedule (exponential
inter-arrival gaps at the target QPS) REGARDLESS of response progress —
the honest way to measure a service under load: a closed loop would slow
its own offered rate the moment the server slows down and hide the
queueing collapse (the coordinated-omission trap). A sender thread walks
the schedule and writes one row per arrival; a receiver thread matches
responses (in-order per connection) against send timestamps.

Usage:

    python tools/loadgen.py --host 127.0.0.1 --port 9000 \
        --data tests/data/rcv1_100.libsvm --qps 500 --duration 5

Prints one JSON line: offered/achieved QPS, ok/shed/err counts, and
p50/p95/p99/max response latency (ms). Importable as ``run_loadgen`` —
bench.py --serve and tests/test_serve.py drive it in-process.

``--endpoints h1:p1,h2:p2`` switches to the FAILOVER driver
(``run_loadgen_failover``): arrivals follow the same open-loop schedule,
but rows travel through the multi-endpoint ``ServeClient``
(serve/client.py) in small pipelined chunks — a killed or draining
replica shows up as failovers and retried tails, not client errors.
This is the harness the takeover/blue-green chaos tests point at a
replica pair to prove "zero client-visible errors". The report's
``endpoints`` section is a PER-ENDPOINT summary (rows answered,
failovers, ejections — ``ServeClient.endpoints_health()``), so a
rolling-restart run shows which replica absorbed each handoff window.
``--blacklist FILE`` joins the fleet's shared endpoint health
(serve/fleethealth.py): ejections propagate to/from every other client
and the router.

``--profile diurnal`` shapes the offered rate over the run as a
piecewise-linear multiplier of ``--qps`` (trough → morning ramp → peak
at 1.6x → evening decay → trough), the day-cycle in miniature that an
elastic fleet must follow: bench.py --serve and the autoscaler chaos
runs use it to force a scale-up mid-run and a drain after the peak.
``flat`` (the default) keeps the constant-rate schedule. The schedule
stays open-loop either way — the multiplier rides on the SCHEDULED
arrival time, not on response progress.

``--zipf-alpha A`` (flat and failover drivers) skews WHICH rows get
sent: row ranks draw from a Zipf(A) law instead of the round-robin
cycle, the popularity shape real key traffic has — the knob the
capacity bench (bench.py --capacity) sweeps to measure the cold
tier's hit rate under realistic skew.

``--label-rate R --label-delay-s D`` switches to the FEEDBACK driver
(``run_loadgen_feedback``) for the online-learning loop
(docs/serving.md "Continuous learning"): every arrival is sent as
``#score <id> <row>`` so the server logs it under a client-chosen id,
and for a seeded fraction ``R`` of rows the client reports the row's
own libsvm label back with ``#label <id> <y>`` after ~``D/2`` seconds —
inside the server's ``label_delay_s`` horizon, so the join lands. The
report adds ``labels_sent`` / ``labels_acked`` / ``labels_missed``.
"""

from __future__ import annotations

import argparse
import json
import socket
import threading
import time
from typing import List, Sequence, Union

import numpy as np
from difacto_tpu.utils.locktrace import mutex

Line = Union[str, bytes]


def _to_bytes(line: Line) -> bytes:
    b = line.encode() if isinstance(line, str) else line
    return b if b.endswith(b"\n") else b + b"\n"


# QPS profiles: (run_fraction, multiplier) anchors, piecewise-linear in
# between. ``diurnal`` is a day cycle compressed into one run — trough,
# ramp, 1.6x peak, decay — sized so a fleet provisioned for the mean
# must scale up through the peak and back down after it.
PROFILES = {
    "flat": ((0.0, 1.0), (1.0, 1.0)),
    "diurnal": ((0.0, 0.3), (0.25, 1.0), (0.5, 1.6),
                (0.75, 0.8), (1.0, 0.3)),
}


def profile_qps(profile, qps: float, frac: float) -> float:
    """The instantaneous target rate at fraction ``frac`` (0..1) of the
    run: ``qps`` times the profile's piecewise-linear multiplier.
    ``profile`` is a name from :data:`PROFILES` or an anchor sequence."""
    anchors = PROFILES[profile] if isinstance(profile, str) else \
        tuple(profile)
    f = min(max(frac, 0.0), 1.0)
    for (f0, m0), (f1, m1) in zip(anchors, anchors[1:]):
        if f <= f1:
            w = 0.0 if f1 <= f0 else (f - f0) / (f1 - f0)
            return qps * (m0 + (m1 - m0) * w)
    return qps * anchors[-1][1]


def make_picker(n: int, zipf_alpha: float, seed: int = 0):
    """Row-index chooser for the senders: ``zipf_alpha <= 0`` cycles
    round-robin (every row equally hot — the historical behavior);
    ``zipf_alpha > 0`` draws ranks from a Zipf law ``p(r) ~ 1/r^alpha``
    over the row set, the skewed key popularity real traffic has and
    the shape the cold tier's hit-rate depends on (docs/perf_notes.md
    "Table capacity"; bench.py --capacity sweeps two alphas). Seeded
    and independent of the arrival-schedule RNG, so turning skew on
    never perturbs the offered-rate schedule."""
    if zipf_alpha <= 0.0 or n <= 1:
        return lambda i: i % n
    w = 1.0 / np.power(np.arange(1, n + 1, dtype=np.float64), zipf_alpha)
    cdf = np.cumsum(w / w.sum())
    rng = np.random.RandomState(seed ^ 0x5A1F)
    return lambda i: int(np.searchsorted(cdf, rng.random_sample()))


def run_loadgen(host: str, port: int, rows: Sequence[Line], qps: float,
                duration_s: float, seed: int = 0,
                recv_timeout: float = 30.0,
                profile: str = "flat", zipf_alpha: float = 0.0) -> dict:
    """Drive the server open-loop at ``qps`` for ``duration_s`` seconds,
    cycling through ``rows``; ``profile`` shapes the rate over the run
    (:func:`profile_qps`), ``zipf_alpha`` skews which rows get sent
    (:func:`make_picker`). Returns the latency/throughput report."""
    rows = [_to_bytes(r) for r in rows]
    if not rows:
        raise ValueError("loadgen needs at least one request row")
    pick = make_picker(len(rows), zipf_alpha, seed)
    rng = np.random.RandomState(seed)
    sock = socket.create_connection((host, port), timeout=recv_timeout)
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:  # pragma: no cover
        pass
    rfile = sock.makefile("rb")

    send_ts: List[float] = []      # monotonic send time per request
    ts_lock = mutex()
    sent = 0

    def sender() -> None:
        nonlocal sent
        t0 = t_next = time.monotonic()
        t_end = t_next + duration_s
        i = 0
        while True:
            now = time.monotonic()
            if now >= t_end:
                break
            if now < t_next:
                time.sleep(min(t_next - now, 0.01))
                continue
            with ts_lock:
                send_ts.append(time.monotonic())
            try:
                sock.sendall(rows[pick(i)])
            except OSError:
                # the server dropped the connection (drain/shutdown
                # mid-run): stop offering, let the receiver tally what
                # came back — rows past this point were never sent
                with ts_lock:
                    send_ts.pop()
                break
            sent += 1
            i += 1
            # exponential gaps: Poisson arrivals at the target rate
            # (profile-shaped at the SCHEDULED time, not the send time).
            # Falling behind (a slow send) is NOT forgiven — the next
            # arrival time advances by the schedule, keeping the offered
            # rate honest even when the socket pushes back.
            t_next += rng.exponential(1.0 / profile_qps(
                profile, qps, (t_next - t0) / duration_s))
        # half-close: the server reader sees EOF, drains queued futures,
        # and the responses for every sent row still arrive below
        try:
            sock.shutdown(socket.SHUT_WR)
        except OSError:
            pass

    lat_ok: List[float] = []
    n_ok = n_shed = n_err = 0

    def receiver() -> None:
        nonlocal n_ok, n_shed, n_err
        i = 0
        while True:
            try:
                line = rfile.readline()
            except (socket.timeout, OSError):
                break
            if not line:
                break
            now = time.monotonic()
            with ts_lock:
                t0 = send_ts[i] if i < len(send_ts) else None
            i += 1
            if line.startswith(b"!shed"):
                n_shed += 1
            elif line.startswith(b"!err"):
                n_err += 1
            else:
                n_ok += 1
                if t0 is not None:
                    lat_ok.append(now - t0)

    st = threading.Thread(target=sender, name="loadgen-send")
    rt = threading.Thread(target=receiver, name="loadgen-recv")
    t_start = time.monotonic()
    st.start()
    rt.start()
    st.join()
    rt.join()
    elapsed = time.monotonic() - t_start
    rfile.close()
    sock.close()

    out = {
        "target_qps": qps,
        "duration_s": round(duration_s, 3),
        "sent": sent,
        "offered_qps": round(sent / max(duration_s, 1e-9), 1),
        "ok": n_ok,
        "shed": n_shed,
        "err": n_err,
        "shed_rate": round(n_shed / max(sent, 1), 4),
        # completed responses over the whole drain window: the rate the
        # service actually sustained
        "achieved_qps": round(n_ok / max(elapsed, 1e-9), 1),
    }
    if lat_ok:
        lat = np.asarray(lat_ok) * 1e3
        p50, p95, p99 = np.percentile(lat, [50, 95, 99])
        out.update(p50_ms=round(float(p50), 3), p95_ms=round(float(p95), 3),
                   p99_ms=round(float(p99), 3),
                   max_ms=round(float(lat.max()), 3))
    return out


def _row_label(row: bytes) -> float:
    """The row's own leading libsvm label token (the ground truth the
    feedback join replays), 0.0 when the row has none."""
    try:
        return float(row.split(None, 1)[0])
    except (ValueError, IndexError):
        return 0.0


def run_loadgen_feedback(host: str, port: int, rows: Sequence[Line],
                         qps: float, duration_s: float,
                         label_delay_s: float = 0.5,
                         label_rate: float = 0.5, seed: int = 0,
                         recv_timeout: float = 30.0) -> dict:
    """Open-loop driver for the serve→log→train feedback join: rows go
    out as ``#score <id> <row>`` and a seeded ``label_rate`` fraction
    get their own label reported back (``#label <id> <y>``) after half
    the ``label_delay_s`` horizon — delayed, but inside the window.
    Responses stay in request order per connection (scores resolve
    through the batcher, label acks are raw control replies, the writer
    drains both in admission order), so one receiver matches both."""
    rows = [_to_bytes(r) for r in rows]
    if not rows:
        raise ValueError("loadgen needs at least one request row")
    rng = np.random.RandomState(seed)
    sock = socket.create_connection((host, port), timeout=recv_timeout)
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:  # pragma: no cover
        pass
    rfile = sock.makefile("rb")

    # per sent line: ("score", send_t) or ("label", None), in send order
    meta: List[tuple] = []
    ts_lock = mutex()
    sent = labels_sent = 0

    def sender() -> None:
        nonlocal sent, labels_sent
        import collections
        pending = collections.deque()   # (due_t, rid, y), due_t ascending
        t_next = time.monotonic()
        t_end = t_next + duration_s
        i = 0
        try:
            while True:
                now = time.monotonic()
                # due labels first: constant delay keeps the deque sorted
                while pending and pending[0][0] <= now:
                    _, rid, y = pending.popleft()
                    with ts_lock:
                        meta.append(("label", None))
                    sock.sendall(b"#label "
                                 + (b"%d %g\n" % (rid, y)))
                    labels_sent += 1
                if now >= t_end:
                    break
                if now < t_next:
                    time.sleep(min(t_next - now, 0.01))
                    continue
                row = rows[i % len(rows)]
                with ts_lock:
                    meta.append(("score", time.monotonic()))
                sock.sendall(b"#score " + (b"%d " % i) + row)
                sent += 1
                if label_rate > 0 and rng.random_sample() < label_rate:
                    pending.append((now + label_delay_s * 0.5, i,
                                    _row_label(row)))
                i += 1
                t_next += rng.exponential(1.0 / qps)
            # flush the tail of scheduled labels (their rows are already
            # logged; an early report still joins) before half-closing
            while pending:
                _, rid, y = pending.popleft()
                with ts_lock:
                    meta.append(("label", None))
                sock.sendall(b"#label " + (b"%d %g\n" % (rid, y)))
                labels_sent += 1
        except OSError:
            # connection dropped mid-run: the receiver tallies what
            # came back; the unsent line's meta entry is harmless (the
            # receiver indexes by reply order and stops at EOF)
            pass
        try:
            sock.shutdown(socket.SHUT_WR)
        except OSError:
            pass

    lat_ok: List[float] = []
    n_ok = n_shed = n_err = 0
    labels_acked = labels_missed = label_errs = 0

    def receiver() -> None:
        nonlocal n_ok, n_shed, n_err, labels_acked, labels_missed
        nonlocal label_errs
        i = 0
        while True:
            try:
                line = rfile.readline()
            except (socket.timeout, OSError):
                break
            if not line:
                break
            now = time.monotonic()
            with ts_lock:
                kind, t0 = meta[i] if i < len(meta) else ("score", None)
            i += 1
            if kind == "label":
                if line.startswith(b"!err"):
                    label_errs += 1
                elif b"true" in line:
                    labels_acked += 1
                else:
                    labels_missed += 1   # row resolved past its horizon
            elif line.startswith(b"!shed"):
                n_shed += 1
            elif line.startswith(b"!err"):
                n_err += 1
            else:
                n_ok += 1
                if t0 is not None:
                    lat_ok.append(now - t0)

    st = threading.Thread(target=sender, name="loadgen-send")
    rt = threading.Thread(target=receiver, name="loadgen-recv")
    t_start = time.monotonic()
    st.start()
    rt.start()
    st.join()
    rt.join()
    elapsed = time.monotonic() - t_start
    rfile.close()
    sock.close()

    out = {
        "target_qps": qps,
        "duration_s": round(duration_s, 3),
        "sent": sent,
        "offered_qps": round(sent / max(duration_s, 1e-9), 1),
        "ok": n_ok,
        "shed": n_shed,
        "err": n_err,
        "shed_rate": round(n_shed / max(sent, 1), 4),
        "achieved_qps": round(n_ok / max(elapsed, 1e-9), 1),
        "labels_sent": labels_sent,
        "labels_acked": labels_acked,
        "labels_missed": labels_missed,
        "label_errs": label_errs,
    }
    if lat_ok:
        lat = np.asarray(lat_ok) * 1e3
        p50, p95, p99 = np.percentile(lat, [50, 95, 99])
        out.update(p50_ms=round(float(p50), 3), p95_ms=round(float(p95), 3),
                   p99_ms=round(float(p99), 3),
                   max_ms=round(float(lat.max()), 3))
    return out


def run_loadgen_failover(endpoints, rows: Sequence[Line], qps: float,
                         duration_s: float, seed: int = 0,
                         retries: int = 8, chunk: int = 64,
                         timeout: float = 30.0, blacklist=None,
                         profile: str = "flat",
                         zipf_alpha: float = 0.0) -> dict:
    """Open-loop schedule over the failover ``ServeClient``: due rows
    are pipelined in chunks of at most ``chunk``; a dropped replica is
    absorbed by the client (reconnect / next endpoint / resend tail),
    so only genuine ``!err`` rows or exhausted budgets count as errors.
    Latency is measured from each row's SCHEDULED arrival, so queueing
    behind a failover window is charged honestly. ``blacklist`` (path or
    FleetHealth) wires the client into the fleet's shared endpoint
    health (serve/fleethealth.py). The report's ``endpoints`` list is
    the per-endpoint summary — rows answered, failovers absorbed,
    ejections — so a rollout chaos run shows WHICH replica carried the
    handoff window, not just fleet totals. ``profile`` shapes the rate
    over the run (:func:`profile_qps`)."""
    from difacto_tpu.serve import ServeClient
    rows = [_to_bytes(r) for r in rows]
    if not rows:
        raise ValueError("loadgen needs at least one request row")
    pick = make_picker(len(rows), zipf_alpha, seed)
    rng = np.random.RandomState(seed)
    client = ServeClient(endpoints=endpoints, retries=retries,
                         backoff_s=0.02, backoff_max_s=0.5,
                         timeout=timeout, blacklist=blacklist)
    lat_ok: List[float] = []
    n_ok = n_shed = n_err = sent = 0
    i = 0
    t_start = time.monotonic()
    t_next, t_end = t_start, t_start + duration_s
    try:
        while time.monotonic() < t_end:
            due = []
            now = time.monotonic()
            while t_next <= now and t_next < t_end and len(due) < chunk:
                due.append((rows[pick(i)], t_next))
                i += 1
                t_next += rng.exponential(1.0 / profile_qps(
                    profile, qps, (t_next - t_start) / duration_s))
            if not due:
                time.sleep(min(max(t_next - now, 0.0), 0.01))
                continue
            sent += len(due)
            try:
                resp = client.score_lines([r for r, _ in due])
            except (OSError, ConnectionError):
                n_err += len(due)   # every endpoint's budget exhausted
                continue
            done = time.monotonic()
            for (_, t0), line in zip(due, resp):
                if line.startswith(b"!shed"):
                    n_shed += 1
                elif line.startswith(b"!err"):
                    n_err += 1
                else:
                    n_ok += 1
                    lat_ok.append(done - t0)
    finally:
        failovers = client.failovers
        endpoints_health = client.endpoints_health()
        client.close()
    elapsed = time.monotonic() - t_start
    out = {
        "target_qps": qps,
        "duration_s": round(duration_s, 3),
        "sent": sent,
        "offered_qps": round(sent / max(duration_s, 1e-9), 1),
        "ok": n_ok,
        "shed": n_shed,
        "err": n_err,
        "shed_rate": round(n_shed / max(sent, 1), 4),
        "achieved_qps": round(n_ok / max(elapsed, 1e-9), 1),
        "failovers": failovers,
        "endpoints": endpoints_health,
    }
    if lat_ok:
        lat = np.asarray(lat_ok) * 1e3
        p50, p95, p99 = np.percentile(lat, [50, 95, 99])
        out.update(p50_ms=round(float(p50), 3), p95_ms=round(float(p95), 3),
                   p99_ms=round(float(p99), 3),
                   max_ms=round(float(lat.max()), 3))
    return out


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int)
    ap.add_argument("--endpoints", default="",
                    help="h1:p1,h2:p2 — drive the multi-endpoint "
                         "failover client instead of one raw socket")
    ap.add_argument("--data", required=True,
                    help="request rows, one per line (e.g. a libsvm file)")
    ap.add_argument("--qps", type=float, default=500.0)
    ap.add_argument("--duration", type=float, default=5.0)
    ap.add_argument("--max-rows", type=int, default=100000,
                    help="cap on distinct rows read from --data")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--profile", default="flat",
                    choices=sorted(PROFILES),
                    help="shape of the offered rate over the run: "
                         "flat, or the diurnal trough/peak cycle")
    ap.add_argument("--zipf-alpha", type=float, default=0.0,
                    help="skew the row-selection distribution: 0 cycles "
                         "round-robin, >0 draws row ranks from a "
                         "Zipf(alpha) law — the popularity shape the "
                         "cold-tier hit rate depends on")
    ap.add_argument("--label-rate", type=float, default=0.0,
                    help="feedback mode: report each row's own label "
                         "back for this fraction of #score'd rows")
    ap.add_argument("--label-delay-s", type=float, default=0.5,
                    help="feedback mode: the server-side join horizon; "
                         "labels go out after half of it")
    ap.add_argument("--retries", type=int, default=8,
                    help="per-endpoint retry budget (failover mode)")
    ap.add_argument("--blacklist", default="",
                    help="shared endpoint-health file (failover mode; "
                         "serve/fleethealth.py)")
    args = ap.parse_args()
    if not args.endpoints and args.port is None:
        ap.error("pass --port or --endpoints")
    with open(args.data, "rb") as f:
        rows = [l for l in f.read().splitlines() if l.strip()]
    rows = rows[:args.max_rows]
    if args.endpoints:
        import os
        import sys as _sys
        _sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        rep = run_loadgen_failover(
            args.endpoints, rows, args.qps, args.duration,
            seed=args.seed, retries=args.retries,
            blacklist=args.blacklist or None, profile=args.profile,
            zipf_alpha=args.zipf_alpha)
        print(json.dumps(rep))
        # the per-endpoint summary, one human line each: which replica
        # answered the rows, who failed over, who got ejected
        import sys
        for e in rep["endpoints"]:
            print(f"# {e['host']}:{e['port']} rows={e['rows']} "
                  f"fails={e['fails']} ejections={e['ejections']} "
                  f"ejected={e['ejected']} active={e['active']}",
                  file=sys.stderr)
    elif args.label_rate > 0:
        print(json.dumps(run_loadgen_feedback(
            args.host, args.port, rows, args.qps, args.duration,
            label_delay_s=args.label_delay_s, label_rate=args.label_rate,
            seed=args.seed)))
    else:
        print(json.dumps(run_loadgen(args.host, args.port, rows, args.qps,
                                     args.duration, seed=args.seed,
                                     profile=args.profile,
                                     zipf_alpha=args.zipf_alpha)))


if __name__ == "__main__":
    main()
