#!/usr/bin/env python
"""Zero-downtime replica takeover driver: spawn -> warm -> handoff -> exit.

Sequences the SO_REUSEPORT takeover the serve subsystem supports
(docs/serving.md runbook): the incumbent keeps serving on its port while
a successor process binds the SAME port (both run ``serve_takeover=1``),
loads + warms its model, and writes its ready file; then the incumbent
is told ``#handoff <ready_file>`` and drains — established connections
finish on the incumbent, new connections land on the successor, and no
request window goes unanswered.

    python tools/takeover.py --host 127.0.0.1 --port 9000 \
        --model /models/ctr --serve-arg serve_batch_size=256

Prints one JSON line: incumbent/successor ids, successor warm time, and
``takeover_gap_ms`` — the time from handoff to the first fresh
connection answered ready by the successor (an upper bound on any
client-visible gap; with SO_REUSEPORT the successor was already
accepting throughout, so the true gap is ~0).

The sequencing lives in ``difacto_tpu/serve/fleet.py`` (run_takeover is
the single-replica primitive of the health-gated rolling restart —
``tools/fleet.py roll`` repeats it across a whole replica list). This
wrapper keeps the one-replica CLI and the ``run_takeover`` import the
tests use.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from difacto_tpu.serve.fleet import (  # noqa: E402  (path setup first)
    EndpointRpc, run_takeover, spawn_successor)

# back-compat aliases: scripts importing the pre-fleet module layout
_Rpc = EndpointRpc

__all__ = ["run_takeover", "spawn_successor", "EndpointRpc"]


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--model", required=True,
                    help="model_in for the successor process")
    ap.add_argument("--serve-arg", action="append", default=[],
                    help="extra k=v for the successor (repeatable)")
    ap.add_argument("--wait-s", type=float, default=180.0)
    args = ap.parse_args()
    print(json.dumps(run_takeover(args.host, args.port, args.model,
                                  extra=args.serve_arg,
                                  wait_s=args.wait_s)))


if __name__ == "__main__":
    main()
