#!/usr/bin/env python
"""Zero-downtime replica takeover driver: spawn -> warm -> handoff -> exit.

Sequences the SO_REUSEPORT takeover the serve subsystem supports
(docs/serving.md runbook): the incumbent keeps serving on its port while
a successor process binds the SAME port (both run ``serve_takeover=1``),
loads + warms its model, and writes its ready file; then the incumbent
is told ``#handoff <ready_file>`` and drains — established connections
finish on the incumbent, new connections land on the successor, and no
request window goes unanswered.

    python tools/takeover.py --host 127.0.0.1 --port 9000 \
        --model /models/ctr --serve-arg serve_batch_size=256

Prints one JSON line: incumbent/successor ids, successor warm time, and
``takeover_gap_ms`` — the time from handoff to the first fresh
connection answered ready by the successor (an upper bound on any
client-visible gap; with SO_REUSEPORT the successor was already
accepting throughout, so the true gap is ~0).

The routing subtlety this driver encodes: once two processes listen on
one port, a FRESH connection hashes to either of them — but an
ESTABLISHED connection stays with its owner. So the driver connects to
the incumbent BEFORE the successor binds and holds that connection; the
later ``#handoff`` provably reaches the incumbent. (A mis-routed handoff
is also safe — a replica that owns the named ready file refuses it.)

Importable as ``run_takeover`` — tests drive it with an in-process
``spawn_fn`` instead of a subprocess successor.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class _Rpc:
    """One newline-JSON control channel over a held TCP connection."""

    def __init__(self, host: str, port: int, timeout: float = 10.0):
        self.sock = socket.create_connection((host, port),
                                             timeout=timeout)
        self.rfile = self.sock.makefile("rb")

    def call(self, line: str) -> dict:
        self.sock.sendall(line.encode() + b"\n")
        resp = self.rfile.readline()
        if not resp:
            raise ConnectionError("connection closed")
        if resp.startswith(b"!err"):
            raise ConnectionError(resp.rstrip(b"\n").decode())
        return json.loads(resp)

    def close(self) -> None:
        try:
            self.rfile.close()
            self.sock.close()
        except OSError:
            pass


def _fresh_health(host: str, port: int, timeout: float = 5.0) -> dict:
    rpc = _Rpc(host, port, timeout=timeout)
    try:
        return rpc.call("#health")
    finally:
        rpc.close()


def spawn_successor(model: str, port: int, ready_file: str,
                    extra=()) -> "subprocess.Popen":
    """Default successor: a fresh task=serve process on the shared port
    (serve_takeover=1 so the kernel accepts the second binding). Its
    output goes to ``<ready_file>.log`` — NOT the driver's inherited
    pipes, which a parent capturing the driver's output would otherwise
    wait on for the whole life of the successor."""
    args = [sys.executable, "-m", "difacto_tpu", "task=serve",
            f"model_in={model}", f"serve_port={port}", "serve_takeover=1",
            f"serve_ready_file={ready_file}", *extra]
    logf = open(ready_file + ".log", "ab")
    try:
        return subprocess.Popen(args, cwd=REPO, stdin=subprocess.DEVNULL,
                                stdout=logf, stderr=logf,
                                start_new_session=True)
    finally:
        logf.close()   # the child holds its own descriptor


def run_takeover(host: str, port: int, model: str = "", extra=(),
                 spawn_fn=None, wait_s: float = 180.0,
                 poll_s: float = 0.05) -> dict:
    """Sequence one takeover; returns the report dict. ``spawn_fn``
    (ready_file -> handle with .poll(), or None) overrides the
    subprocess successor for in-process tests."""
    # 1. hold a connection to the incumbent while it is the only
    #    listener — #handoff later rides this connection, immune to
    #    SO_REUSEPORT's fresh-connection hashing
    incumbent = _Rpc(host, port)
    try:
        h0 = incumbent.call("#health")
        if not h0.get("takeover"):
            raise SystemExit(
                "incumbent is not running serve_takeover=1 — restart it "
                "once with the knob before zero-downtime handoffs work")
        incumbent_id = h0["server_id"]

        # 2. spawn the successor; it loads + warms, binds the shared
        #    port, then writes its ready file
        fd, ready_file = tempfile.mkstemp(suffix=".ready")
        os.close(fd)
        os.unlink(ready_file)   # the successor's write IS the signal
        t0 = time.monotonic()
        proc = (spawn_fn(ready_file) if spawn_fn is not None
                else spawn_successor(model, port, ready_file, extra))
        while not os.path.exists(ready_file):
            if proc is not None and getattr(proc, "poll", None) \
                    and proc.poll() is not None:
                raise RuntimeError(
                    f"successor exited rc={proc.poll()} before ready")
            if time.monotonic() - t0 > wait_s:
                raise TimeoutError(
                    f"successor not ready after {wait_s:.0f}s")
            time.sleep(poll_s)
        warm_s = time.monotonic() - t0

        # 3. handoff: the incumbent confirms the ready file, drains and
        #    exits; its established connections finish first
        t1 = time.monotonic()
        res = incumbent.call(f"#handoff {ready_file}")

        # 4. fresh connections answer from the successor, ready
        while True:
            try:
                h = _fresh_health(host, port)
                if h.get("server_id") != incumbent_id \
                        and h.get("status") == "ready":
                    break
            except (OSError, ConnectionError, ValueError):
                pass
            if time.monotonic() - t1 > wait_s:
                raise TimeoutError("takeover never completed: fresh "
                                   "connections still reach the "
                                   "incumbent (or nothing)")
            time.sleep(poll_s)
        out = {"ok": True, "incumbent": incumbent_id,
               "successor": h["server_id"],
               "model_generation": h.get("model_generation"),
               "warm_s": round(warm_s, 3), "handoff": res,
               "takeover_gap_ms":
                   round((time.monotonic() - t1) * 1e3, 1)}
        if spawn_fn is None:
            out["successor_log"] = ready_file + ".log"
        return out
    finally:
        incumbent.close()


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--model", required=True,
                    help="model_in for the successor process")
    ap.add_argument("--serve-arg", action="append", default=[],
                    help="extra k=v for the successor (repeatable)")
    ap.add_argument("--wait-s", type=float, default=180.0)
    args = ap.parse_args()
    print(json.dumps(run_takeover(args.host, args.port, args.model,
                                  extra=args.serve_arg,
                                  wait_s=args.wait_s)))


if __name__ == "__main__":
    main()
