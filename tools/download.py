#!/usr/bin/env python
"""Dataset fetcher — the reference's ``tools/download.sh`` equivalent
(/root/reference/tools/download.sh:1-46: gisette / rcv1 from the LIBSVM
site, criteo-kaggle rec files from data.dmlc.ml).

Two modes:

- **download** (default): fetch the real dataset over HTTP, exactly like
  the reference script. Fails fast with a clear message on air-gapped
  machines.
- **--synthesize**: generate a statistically-matched stand-in with a
  PLANTED ground-truth model. Feature-count / sparsity / skew marginals
  match the real dataset; labels are sampled from a planted
  linear+low-rank-interaction logistic model, so (a) AUC is meaningful,
  (b) the achievable ceiling is KNOWN — the generator writes a
  ``<name>.meta.json`` with the planted model's own AUC on the generated
  rows (the Bayes-ish ceiling a perfect learner approaches), and (c) FM
  beats plain LR iff the learner actually exploits the planted pairwise
  interactions.

Usage:
    python tools/download.py gisette [--data-dir data]
    python tools/download.py rcv1 --synthesize
    python tools/download.py criteo --synthesize --rows 2000000
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

LIBSVM_URL = "https://www.csie.ntu.edu.tw/~cjlin/libsvmtools/datasets/binary"
DMLC_URL = "http://data.dmlc.ml/difacto/datasets"

DATASETS = {
    "gisette": [f"{LIBSVM_URL}/gisette_scale.bz2",
                f"{LIBSVM_URL}/gisette_scale.t.bz2"],
    "rcv1": [f"{LIBSVM_URL}/rcv1_train.binary.bz2"],
    "criteo": [f"{DMLC_URL}/criteo_kaggle/criteo_train.rec",
               f"{DMLC_URL}/criteo_kaggle/criteo_val.rec"],
    "ctra": [f"{DMLC_URL}/ctra/ctra_train.rec",
             f"{DMLC_URL}/ctra/ctra_val.rec"],
}


def download(name: str, data_dir: str) -> int:
    import bz2
    import shutil
    import urllib.request
    os.makedirs(data_dir, exist_ok=True)
    for url in DATASETS[name]:
        fname = os.path.join(data_dir, os.path.basename(url))
        out = fname[:-4] if fname.endswith(".bz2") else fname
        if os.path.exists(out):
            print(f"{out} exists, skipping")
            continue
        print(f"fetching {url} ...")
        # stream to a .part temp and rename on success: an interrupted
        # download must never leave a truncated file that a later run
        # skips as complete
        tmp = out + ".part"
        try:
            with urllib.request.urlopen(url, timeout=30) as resp:
                if fname.endswith(".bz2"):
                    with open(tmp, "wb") as f:
                        shutil.copyfileobj(bz2.BZ2File(resp), f)
                else:
                    with open(tmp, "wb") as f:
                        shutil.copyfileobj(resp, f)
            os.replace(tmp, out)
        except Exception as e:  # noqa: BLE001 — any network failure
            if os.path.exists(tmp):
                os.remove(tmp)
            print(f"download failed ({e}).\nThis machine appears to have "
                  f"no network egress; use --synthesize to generate a "
                  f"statistically-matched stand-in with a planted "
                  f"ground-truth model instead.", file=sys.stderr)
            return 1
    return 0


# --------------------------------------------------------------- synthesis
def _planted_auc(prob: np.ndarray, label: np.ndarray) -> float:
    """AUC of the planted true probabilities against the sampled labels —
    the ceiling any learner on this data approaches."""
    order = np.argsort(prob, kind="stable")
    ranks = np.empty(len(prob))
    ranks[order] = np.arange(1, len(prob) + 1)
    pos = label > 0
    n_pos, n_neg = pos.sum(), (~pos).sum()
    if n_pos == 0 or n_neg == 0:
        return 0.5
    return float((ranks[pos].sum() - n_pos * (n_pos + 1) / 2)
                 / (n_pos * n_neg))


def _write_meta(path: str, meta: dict) -> None:
    with open(path + ".meta.json", "w") as f:
        json.dump(meta, f, indent=1)
    print(f"wrote {path} ({meta['rows']} rows; planted-model AUC "
          f"{meta['planted_auc']:.4f})")


def _sample_labels(rng, score: np.ndarray) -> tuple:
    prob = 1.0 / (1.0 + np.exp(-score))
    label = (rng.random_sample(len(prob)) < prob).astype(np.int8)
    return prob, label


def synth_gisette(data_dir: str, seed: int = 0) -> None:
    """Gisette stand-in: 6000 train + 1000 test rows, 5000 dense scaled
    features (the real set is a dense digit-pair task with many probe
    features). Planted: sparse linear model over 300 informative features
    + rank-8 interactions on the first 64."""
    rng = np.random.RandomState(seed)
    n_feat, k = 5000, 8
    w = np.zeros(n_feat)
    informative = rng.permutation(n_feat)[:300]
    w[informative] = rng.randn(300) * 1.1
    V = np.zeros((n_feat, k))
    V[informative[:64]] = rng.randn(64, k) * 0.4
    for split, nrows in (("", 6000), (".t", 1000)):
        X = np.clip(rng.randn(nrows, n_feat) * 0.45, -1, 1)
        X[rng.random_sample(X.shape) < 0.35] = 0.0  # real set is ~65% dense
        xv = X @ V
        inter = 0.5 * ((xv ** 2).sum(1) - ((X ** 2) @ (V ** 2)).sum(1))
        lin = X @ w
        prob, label = _sample_labels(rng, lin + inter)
        path = os.path.join(data_dir, f"gisette_scale{split}")
        _write_libsvm(path, label, X)
        _write_meta(path, {
            "dataset": "gisette (synthetic stand-in)", "rows": nrows,
            "n_features": n_feat, "planted_auc": _planted_auc(prob, label),
            # ceiling for a LINEAR model (no interaction term) — what
            # l1-LR approaches; FM approaches planted_auc
            "planted_linear_auc": _planted_auc(
                1 / (1 + np.exp(-lin)), label),
            "seed": seed})


def _write_libsvm(path: str, label: np.ndarray, X: np.ndarray) -> None:
    """Dense matrix -> libsvm text (zeros elided), ±1 labels."""
    with open(path, "w") as f:
        for i in range(X.shape[0]):
            nz = np.nonzero(X[i])[0]
            feats = " ".join(f"{j + 1}:{X[i, j]:.4g}" for j in nz)
            f.write(f"{'+1' if label[i] else '-1'} {feats}\n")


def synth_rcv1(data_dir: str, seed: int = 0, rows: int = 20242) -> None:
    """rcv1_train.binary stand-in: 20,242 rows x 47,236 features, ~73
    nnz/row, zipf-skewed feature popularity (deduped per row, ids unique
    and sorted like real libsvm), tf-idf-like values. Planted linear model
    over every feature (text categorization is near-linear: each term
    carries some signal; popular terms dominate the score)."""
    rng = np.random.RandomState(seed)
    n_feat = 47236
    w = np.concatenate([[0.0], rng.randn(n_feat)])
    path = os.path.join(data_dir, "rcv1_train.binary")
    probs_all, labels_all = [], []
    scale = None
    with open(path, "w") as f:
        for start in range(0, rows, 4096):
            n = min(4096, rows - start)
            nnz = np.clip(rng.poisson(95, n), 8, 300)
            row_ids, row_vals, scores = [], [], np.zeros(n)
            for i in range(n):
                ids = np.unique((rng.zipf(1.45, nnz[i]) - 1) % n_feat) + 1
                vals = np.round(rng.exponential(0.09, len(ids)) + 0.01, 4)
                row_ids.append(ids)
                row_vals.append(vals)
                scores[i] = (w[ids] * vals).sum()
            if scale is None:  # deterministic: fixed by the first block
                scale = 2.5 / max(scores.std(), 1e-9)
            prob, label = _sample_labels(rng, scores * scale)
            probs_all.append(prob)
            labels_all.append(label)
            lines = [
                f"{'+1' if label[i] else '-1'} "
                + " ".join(f"{j}:{v}" for j, v in
                           zip(row_ids[i], row_vals[i]))
                for i in range(n)]
            f.write("\n".join(lines) + "\n")
    _write_meta(path, {
        "dataset": "rcv1_train.binary (synthetic stand-in)", "rows": rows,
        "n_features": n_feat,
        "planted_auc": _planted_auc(np.concatenate(probs_all),
                                    np.concatenate(labels_all)),
        "seed": seed})


def synth_criteo(data_dir: str, seed: int = 0, rows: int = 2_000_000,
                 val_fraction: float = 0.1) -> None:
    """Criteo-kaggle stand-in in the reference's criteo tab format
    (label \\t 13 ints \\t 26 categoricals): zipf-skewed token popularity
    (~100k tokens/field), planted per-token linear weights + rank-8
    interactions across 8 of the 26 categorical fields, plus log-scaled
    integer-feature effects. Train and val splits share the planted model."""
    rng = np.random.RandomState(seed)
    n_tok, k = 100_000, 8
    # planted per-field token weight tables (vectorized lookup); scales
    # tuned so the planted ceiling lands near real criteo models
    # (test AUC ~0.80) rather than an unrealistically separable task
    w_tab = rng.randn(26, n_tok) * 0.20
    # interactions: fields 0..7 get token embeddings
    v_tab = rng.randn(8, n_tok, k) * 0.16
    w_int = rng.randn(13) * 0.05
    meta = {}
    for split, n in (("train", rows), ("val", int(rows * val_fraction))):
        path = os.path.join(data_dir, f"criteo_{split}.txt")
        probs_all, labels_all = [], []
        with open(path, "w") as f:
            for start in range(0, n, 65536):
                b = min(65536, n - start)
                ints = rng.randint(0, 1000, (b, 13))
                toks = (rng.zipf(1.25, (b, 26)) - 1) % n_tok
                score = (np.take_along_axis(w_tab.T, toks, axis=0).sum(1)
                         + (np.log1p(ints) * w_int).sum(1) - 1.3)
                emb = v_tab[np.arange(8)[None, :], toks[:, :8]]  # [b,8,k]
                xv = emb.sum(1)
                score += 0.5 * ((xv ** 2).sum(1) - (emb ** 2).sum((1, 2)))
                prob, label = _sample_labels(rng, score)
                probs_all.append(prob)
                labels_all.append(label)
                cats = np.char.add("c", toks.astype(str))
                cols = np.concatenate([label.astype(str)[:, None],
                                       ints.astype(str), cats], axis=1)
                f.write("\n".join("\t".join(r) for r in cols) + "\n")
        meta[split] = _planted_auc(np.concatenate(probs_all),
                                   np.concatenate(labels_all))
        _write_meta(path, {
            "dataset": f"criteo-kaggle {split} (synthetic stand-in)",
            "rows": n, "tokens_per_field": n_tok,
            "planted_auc": meta[split], "seed": seed})


def synth_avazu(data_dir: str, seed: int = 0, rows: int = 2_000_000,
                val_fraction: float = 0.1) -> None:
    """Avazu CTR stand-in in libsvm form: 21 categorical fields per row
    (one token each — site/app/device/context ids), zipf token popularity
    over ~300k tokens/field, CTR ~17% (the real set's rate). Planted
    per-token weights + rank-8 interactions across 6 fields. Feature id =
    field * 300000 + token + 1, so rows are sorted-unique 21-nnz binary —
    the uniform-width panel layout."""
    rng = np.random.RandomState(seed)
    n_tok, n_field, k = 300_000, 21, 8
    w_tab = rng.randn(n_field, n_tok) * 0.22
    v_tab = rng.randn(6, n_tok, k) * 0.17
    for split, n in (("train", rows), ("val", int(rows * val_fraction))):
        path = os.path.join(data_dir, f"avazu_{split}.libsvm")
        probs_all, labels_all = [], []
        with open(path, "w") as f:
            for start in range(0, n, 65536):
                b = min(65536, n - start)
                toks = (rng.zipf(1.3, (b, n_field)) - 1) % n_tok
                score = np.take_along_axis(w_tab.T, toks,
                                           axis=0).sum(1) - 2.0
                emb = v_tab[np.arange(6)[None, :], toks[:, :6]]
                xv = emb.sum(1)
                score += 0.5 * ((xv ** 2).sum(1) - (emb ** 2).sum((1, 2)))
                prob, label = _sample_labels(rng, score)
                probs_all.append(prob)
                labels_all.append(label)
                ids = toks + np.arange(n_field)[None, :] * n_tok + 1
                lines = [
                    ("+1 " if label[i] else "-1 ")
                    + " ".join(f"{j}:1" for j in ids[i])
                    for i in range(b)]
                f.write("\n".join(lines) + "\n")
        _write_meta(path, {
            "dataset": f"avazu {split} (synthetic stand-in)", "rows": n,
            "tokens_per_field": n_tok,
            "planted_auc": _planted_auc(np.concatenate(probs_all),
                                        np.concatenate(labels_all)),
            "seed": seed})


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("name", choices=sorted(DATASETS) + ["avazu"])
    ap.add_argument("--data-dir", default="data")
    ap.add_argument("--synthesize", action="store_true",
                    help="generate a planted-model stand-in instead of "
                         "downloading (for air-gapped machines)")
    ap.add_argument("--rows", type=int, default=0,
                    help="row count for synthesized criteo/rcv1 "
                         "(default: dataset-matched)")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    if not args.synthesize:
        if args.name == "avazu":
            print("avazu has no public mirror in the reference's "
                  "download.sh; use --synthesize", file=sys.stderr)
            return 1
        return download(args.name, args.data_dir)
    os.makedirs(args.data_dir, exist_ok=True)
    if args.name == "gisette":
        synth_gisette(args.data_dir, args.seed)
    elif args.name == "rcv1":
        synth_rcv1(args.data_dir, args.seed,
                   rows=args.rows or 20242)
    elif args.name == "criteo":
        synth_criteo(args.data_dir, args.seed,
                     rows=args.rows or 2_000_000)
    elif args.name == "avazu":
        synth_avazu(args.data_dir, args.seed,
                    rows=args.rows or 2_000_000)
    else:
        print(f"no synthesizer for {args.name} (ctra has no published "
              f"schema to match)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
