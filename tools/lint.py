#!/usr/bin/env python
"""difacto-lint entry point — `make lint` runs this.

Thin wrapper so the analyzer works from a checkout without installing
the package: ``python tools/lint.py [paths...] [--format=...]``.
See docs/static_analysis.md for the rule catalog and the suppression /
baseline workflow.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from difacto_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
