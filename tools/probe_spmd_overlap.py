"""Probe: 2-process streamed SPMD epoch wall time (verdict weak #6).

Times the synchronized-step multi-host schedule on the virtual CPU mesh —
the per-step DCN control-plane allgather used to sit serially between
device steps; the exchange pipeline now runs it on a prefetch thread.
Run before/after a change to measure the control-plane overlap.

``--rtt-ms`` injects an artificial delay into every allgather (a stand-in
for real cross-pod DCN latency, which the local loopback rendezvous does
not exhibit): with the serial schedule every injected millisecond lands
on the epoch critical path; with the exchange pipeline it overlaps the
device steps and the epoch time barely moves.

Usage: python tools/probe_spmd_overlap.py [--rows 2000] [--epochs 4]
           [--rtt-ms 20]
"""
from __future__ import annotations

import argparse
import json
import os
import pathlib
import subprocess
import sys
import tempfile
import time

REPO = pathlib.Path(__file__).resolve().parent.parent

WORKER = r"""
import os, sys, time
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=4")
import jax
jax.config.update("jax_platforms", "cpu")
from difacto_tpu.parallel.multihost import initialize
initialize()
from difacto_tpu.learners import Learner

data, epochs, rtt_ms = sys.argv[1], int(sys.argv[2]), float(sys.argv[3])
if rtt_ms > 0:
    # simulated DCN latency on the control-plane collective (the local
    # loopback rendezvous has none): every ms of it that is NOT
    # overlapped with the device step shows up in the epoch wall time
    import difacto_tpu.parallel.multihost as mh
    _orig = mh.control_allgather_np
    def slow_allgather(arr):
        time.sleep(rtt_ms / 1e3)
        return _orig(arr)
    mh.control_allgather_np = slow_allgather

ln = Learner.create("sgd")
ln.init([("data_in", data), ("V_dim", "4"), ("V_threshold", "0"),
         ("lr", "0.1"), ("l1", "0.1"), ("batch_size", "100"),
         ("max_num_epochs", str(epochs)), ("shuffle", "0"),
         ("report_interval", "0"), ("stop_rel_objv", "0"),
         ("stop_val_auc", "-2"), ("num_jobs_per_epoch", "1"),
         ("hash_capacity", str(1 << 16)),
         ("uniq_cap", "1024"), ("nnz_cap", "1024"),
         ("device_cache_mb", "0"),
         ("mesh_dp", "2"), ("mesh_fs", "4")])
marks = []
ln.add_epoch_end_callback(lambda e, t, v: marks.append(time.perf_counter()))
t0 = time.perf_counter()
ln.run()
if jax.process_index() == 0:
    import numpy as np
    d = np.diff([t0] + marks)
    print("EPOCHS " + " ".join(f"{s:.3f}" for s in d), flush=True)
"""


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=2000)
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--rtt-ms", type=float, default=20.0)
    ap.add_argument("--port", type=int, default=7937,
                    help="rendezvous port; vary it between back-to-back "
                         "runs — a lingering coordinator socket from a "
                         "killed run makes the next rendezvous hang "
                         "silently")
    args = ap.parse_args()

    sys.path.insert(0, str(REPO / "tests"))
    from conftest import write_uniform_libsvm

    with tempfile.TemporaryDirectory() as d:
        data = write_uniform_libsvm(f"{d}/train.libsvm", rows=args.rows,
                                    width=8, id_space=500)
        worker = f"{d}/worker.py"
        with open(worker, "w") as f:
            f.write(WORKER)
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env["PYTHONPATH"] = str(REPO)
        t0 = time.perf_counter()
        proc = subprocess.run(
            [sys.executable, str(REPO / "launch.py"), "-n", "2",
             "--port", str(args.port), "--",
             sys.executable, worker, data, str(args.epochs),
             str(args.rtt_ms)],
            cwd=str(REPO), env=env, capture_output=True, text=True,
            timeout=900)
        wall = time.perf_counter() - t0
    if proc.returncode != 0:
        print(proc.stdout)
        print(proc.stderr)
        raise SystemExit(proc.returncode)
    line = next(ln for ln in proc.stdout.splitlines()
                if ln.startswith("EPOCHS"))
    epochs = [float(v) for v in line.split()[1:]]
    print(json.dumps({
        "rows": args.rows, "epoch_sec": epochs,
        "steady_sec": round(sum(epochs[1:]) / len(epochs[1:]), 3),
        "total_wall_sec": round(wall, 1),
    }))


if __name__ == "__main__":
    main()
