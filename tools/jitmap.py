#!/usr/bin/env python
"""Merged static + dynamic jit-program map — `make jitmap` runs this.

The static half is the JAX flow model difacto-lint builds
(difacto_tpu/analysis/jaxflow.py): every jit program in the tree, its
static/donate argnums, its call sites, and the compile-key verdict —
whether every static is provably drawn from a bounded set (the sticky
shape caps / bucket rungs / config constants) or rides a reasoned
``# lint: ok(jax-recompile)`` suppression. The dynamic half is an
optional jaxtrace dump (DIFACTO_JAXTRACE=1 +
DIFACTO_JAXTRACE_OUT=<path> or jaxtrace.dump()): the per-site
call/compile counts and device->host fetch points a real run recorded.
Both halves key sites by the same ``relpath:lineno`` identity, so
merging answers:

- which jit programs a real run exercised, with how many compiles per
  site (a steady-state run should show compiles << calls everywhere);
- whether any observed jit site is MISSING from the static model, or
  dynamically compiled at a site the model could not declare
  warm-bounded (``unknown_sites`` / ``unwarm_sites``);
- whether any observed device->host transfer happened at a fetch site
  the static model does not list as declared (``unknown_fetches``).

Usage:
  python tools/jitmap.py [--dynamic trace.json] [--json jitmap.json]
                         [--check]

``--check`` exits 1 on any unknown/unwarm dynamic site or undeclared
fetch (CI-able); the default is informational (exit 0).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from difacto_tpu.analysis import core  # noqa: E402
from difacto_tpu.analysis.cli import DEFAULT_PATHS  # noqa: E402
from difacto_tpu.analysis.jaxflow import get_jax_model  # noqa: E402
from difacto_tpu.utils import jaxtrace  # noqa: E402


def build(root=".", dynamic_path=None):
    """{'sites', 'fetch_sites', 'hot_roots', 'dynamic_sites',
    'dynamic_fetches', 'unknown_sites', 'unwarm_sites',
    'unknown_fetches'} — everything the writers, the --check gate and
    the tier-1 test consume."""
    root = Path(root).resolve()
    paths = [p for p in DEFAULT_PATHS if (root / p).exists()]
    project = core.Project(root, paths)
    model = get_jax_model(project)
    doc = model.to_json()
    warm = model.known_warm()
    declared = model.declared_fetches()
    out = {
        "sites": doc["sites"],
        "fetch_sites": doc["fetch_sites"],
        "hot_roots": doc["hot_roots"],
        "dynamic_sites": {},
        "dynamic_fetches": {},
        "unknown_sites": [],
        "unwarm_sites": [],
        "unknown_fetches": [],
    }
    if dynamic_path:
        data = jaxtrace.load(dynamic_path)
        out["dynamic_sites"] = data["sites"]
        out["dynamic_fetches"] = data["fetches"]
        for site in sorted(data["sites"]):
            if site not in model.sites:
                out["unknown_sites"].append(site)
            elif site not in warm:
                out["unwarm_sites"].append(site)
        for site in sorted(data["fetches"]):
            if site not in declared:
                out["unknown_fetches"].append(site)
    return out


def to_text(graph) -> str:
    lines = []
    dyn = graph["dynamic_sites"]
    for sid, rec in sorted(graph["sites"].items()):
        mark = "WARM " if rec["warm_bounded"] else "loose"
        d = dyn.get(sid)
        run = (f"  [{d['compiles']} compiles / {d['calls']} calls]"
               if d else "")
        lines.append(f"{mark} {sid}  jit({rec['target']}) "
                     f"statics={rec['static_argnums']} "
                     f"donate={rec['donate_argnums']}{run}")
        for u in rec["unbounded"]:
            lines.append(f"      suppressed/loose static {u['static']} "
                         f"at {u['call']}: {u['reason'][:90]}")
    lines.append(f"declared fetch points: "
                 f"{len(graph['fetch_sites'])}")
    for site in graph["fetch_sites"]:
        d = graph["dynamic_fetches"].get(site)
        run = f"  [{d['count']}x {d['point']}]" if d else ""
        lines.append(f"  fetch {site}{run}")
    for key in ("unknown_sites", "unwarm_sites", "unknown_fetches"):
        for site in graph[key]:
            lines.append(f"{key.upper().replace('_', '-')}: {site}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="merged static+dynamic jit-program map "
                    "(docs/static_analysis.md v4)")
    ap.add_argument("--root", default=".")
    ap.add_argument("--dynamic", default=None,
                    help="jaxtrace dump (DIFACTO_JAXTRACE_OUT) to merge")
    ap.add_argument("--json", default=None, help="write JSON here")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 on unknown/unwarm dynamic jit sites "
                         "or undeclared fetch points")
    args = ap.parse_args(argv)
    graph = build(args.root, args.dynamic)
    if args.json:
        Path(args.json).write_text(
            json.dumps(graph, indent=1, sort_keys=True) + "\n",
            encoding="utf-8")
        print(f"jitmap: wrote {args.json}")
    print(to_text(graph))
    if args.check and (graph["unknown_sites"] or graph["unwarm_sites"]
                       or graph["unknown_fetches"]):
        print("jitmap: CHECK FAILED — dynamic site/fetch outside the "
              "static model", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
