"""Probe: the V16 step's width-independent ~38 ms floor (verdict weak #4).

The V64 and V16 steps cost the same wall clock even though V16 moves ~4x
fewer bytes. perf_notes attributes the residue to the forward tail: 39
per-column gathers of the combined [w | V] token rows. At V16 those rows
are 17 bf16 elements = 34 bytes — well under the 128-lane tile, so every
gather row is a misaligned read (the same pathology pad_v_rows fixed for
the VVg scatter, where 128-col rows ran 2.3x faster than 32-col at 4x
the bytes).

Variants timed on the real chip at the staged-criteo V16 shape:
  prod      : production step (compact [U, 17] wv gather source)
  pad32     : wv zero-padded to [U, 32] (one 64-byte sublane)
  pad64     : wv zero-padded to [U, 64]
  pad128    : wv zero-padded to [U, 128] (full lane tile)
  twocol    : two panel columns per gather ([2B] index vectors)
  fwd_only  : forward alone (prod), isolating the tail from the backward

Usage: python tools/probe_v16.py [--batch 32768] [--uniq 160000]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import numpy as np  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=32768)
    ap.add_argument("--vdim", type=int, default=16)
    ap.add_argument("--nnz-per-row", type=int, default=39)
    ap.add_argument("--uniq", type=int, default=160_000)
    ap.add_argument("--capacity", type=int, default=1 << 22)
    ap.add_argument("--steps", type=int, default=30)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from bench import build_step, make_batches
    from difacto_tpu.losses import create
    from difacto_tpu.losses.fm import (PRED_CLAMP, _p_vector, _vmask,
                                       _fm_grad_panel_chunked, logit_objv)
    from difacto_tpu.losses.metrics import auc_times_n_binned_jnp
    from difacto_tpu.step import make_step_fns
    from difacto_tpu.updaters.sgd_updater import (SGDUpdaterParam,
                                                  init_state, make_fns)

    k = args.vdim
    param = SGDUpdaterParam(V_dim=k, V_threshold=0, lr=0.1, l1=1e-4,
                            l2=1e-4, V_dtype="bfloat16")
    fns = make_fns(param)
    loss = create("fm", k)
    state0 = init_state(param, args.capacity)
    from difacto_tpu.updaters.sgd_updater import set_all_live
    state0 = set_all_live(param, state0)
    # host-side template: each variant donates its own device copy (a
    # shared device state would be deleted by the first donation)
    state0 = jax.tree.map(np.asarray, state0)

    host_batches = make_batches(4, args.batch, args.nnz_per_row, args.uniq,
                                args.capacity, "zipf")
    batches = [jax.device_put(b) for b, _ in host_batches]
    slots_l = [jnp.asarray(s) for _, s in host_batches]
    u_cap = slots_l[0].shape[0]

    def fwd_variant(pad_to: int = 0, twocol: bool = False):
        """fm_predict_panel_xv with a padded gather source / batched
        columns (experimental twins of losses/fm.py)."""
        def predict_xv(params, pb):
            dt = params.V.dtype
            B, F = pb.idx.shape
            Vm = params.V * _vmask(params).astype(dt)[:, None]
            wv = jnp.concatenate([params.w.astype(dt)[:, None], Vm], axis=1)
            if pad_to > 1 + k:
                wv = jnp.pad(wv, ((0, 0), (0, pad_to - 1 - k)))
            idxT = pb.idx.T
            pred = jnp.zeros((B,), jnp.float32)
            XV = jnp.zeros((B, k), jnp.float32)
            XXVV = jnp.zeros((B, k), jnp.float32)
            if twocol:
                for f in range(0, F - 1, 2):
                    ix = jnp.concatenate([idxT[f], idxT[f + 1]])
                    tok = wv[ix]                     # [2B, width]
                    t2 = tok[:, 1:1 + k].astype(jnp.float32)
                    wc = (tok[:B, 0] + tok[B:, 0]).astype(jnp.float32)
                    ta, tb = t2[:B], t2[B:]
                    pred = pred + wc
                    XV = XV + ta + tb
                    XXVV = XXVV + ta * ta + tb * tb
                for f in range(F - F % 2, F):
                    tok = wv[idxT[f]]
                    t = tok[:, 1:1 + k].astype(jnp.float32)
                    pred = pred + tok[:, 0].astype(jnp.float32)
                    XV = XV + t
                    XXVV = XXVV + t * t
            else:
                for f in range(F):
                    tok = wv[idxT[f]]
                    wc = tok[:, 0].astype(jnp.float32)
                    t = tok[:, 1:1 + k].astype(jnp.float32)
                    pred = pred + wc
                    XV = XV + t
                    XXVV = XXVV + t * t
            pred = pred + 0.5 * jnp.sum(XV * XV - XXVV, axis=1)
            return jnp.clip(pred, -PRED_CLAMP, PRED_CLAMP), XV
        return predict_xv

    def make_train(predict_xv):
        def train_step(state, batch, slots):
            from difacto_tpu.losses import FMParams
            w, V, vmask = fns.get_rows(state, slots)
            params = FMParams(w=w, V=V, v_mask=vmask)
            pred, xv = predict_xv(params, batch)
            objv = logit_objv(pred, batch)
            auc = auc_times_n_binned_jnp(batch.labels, pred, batch.row_mask)
            p = _p_vector(pred, batch)
            gw, gV = _fm_grad_panel_chunked(params, batch, p, xv)
            state = fns.apply_grad(state, slots, gw, gV, vmask)
            return state, objv, auc
        return train_step

    _, prod_step, _ = make_step_fns(fns, loss)

    def fwd_only(state, batch, slots):
        from difacto_tpu.losses import FMParams
        w, V, vmask = fns.get_rows(state, slots)
        pred, xv = loss.predict_xv(FMParams(w=w, V=V, v_mask=vmask), batch)
        return state, logit_objv(pred, batch), jnp.float32(0)

    variants = {
        "prod": prod_step,
        "fwd_only": fwd_only,
        "pad32": make_train(fwd_variant(pad_to=32)),
        "pad64": make_train(fwd_variant(pad_to=64)),
        "pad128": make_train(fwd_variant(pad_to=128)),
        "twocol": make_train(fwd_variant(twocol=True)),
    }

    out = {"batch": args.batch, "vdim": k, "u_cap": int(u_cap),
           "steps": args.steps}
    for name, raw in variants.items():
        # lint: ok(jax-recompile) the probe's PURPOSE is one fresh
        # compile per kernel variant — the loop iterates variants, not
        # steps
        step = jax.jit(raw, donate_argnums=0)
        state = jax.device_put(state0)
        state, objv, _ = step(state, batches[0], slots_l[0])
        # lint: ok(jax-host-sync) completion fence of the timing harness
        float(objv)  # compile + warm
        t0 = time.perf_counter()
        for i in range(args.steps):
            state, objv, _ = step(state, batches[i % 4], slots_l[i % 4])
        # lint: ok(jax-host-sync) completion fence of the timing harness
        float(objv)
        dt = (time.perf_counter() - t0) / args.steps
        out[name] = {"ms_per_step": round(dt * 1e3, 1),
                     "examples_per_sec": round(args.batch / dt, 1)}
        del state
        print(json.dumps({name: out[name]}), flush=True)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
