#!/usr/bin/env python
"""Merged static + dynamic sharding map — `make hlomap` runs this.

The static half is the sharding-flow model difacto-lint builds
(difacto_tpu/analysis/shardflow.py): every fs-scoped state program and
its layout-pin verdict, the pinning builders, the pallas kernel
reachability sets, and the full jit-site universe. The dynamic half is
a compiled-HLO scan (difacto_tpu/utils/hloscan.py): per jit site, the
collectives XLA actually emitted and the memory_analysis() byte
counts, recorded either from a prior run's dump
(``DIFACTO_HLOSCAN_OUT=<path>``) or produced in-process by ``--scan``,
which drives the REAL fs-sharded train step (parallel/capacity.py) and
serve executor (serve/executor.py) on the CPU virtual mesh. Both
halves key programs by the same ``relpath:lineno`` jit-site identity
jaxtrace assigns, so merging answers:

- did ANY compiled program move the fs-sharded capacity axis whole
  across the mesh (an all-gather/all-to-all carrying the table's row
  count — ``table_hits``)?
- did any program's temp arena exceed the per-fs budget
  (``budget_hits``, DIFACTO_HLOSCAN_BUDGET)?
- was any scanned program compiled at a site the static model does not
  know (``unknown_sites`` — a shardflow discovery blind spot)?

Usage:
  python tools/hlomap.py [--scan] [--fs N] [--dynamic scan.json]
                         [--json hlomap.json] [--check]
                         [--rows N] [--budget N]

``--check`` exits 1 on any table-axis collective, budget breach, or
unknown dynamic site (CI-able; ``make ci`` runs ``--scan --fs 4
--check``); the default is informational (exit 0).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# IMPORTANT: nothing above may import jax — --scan must set the
# platform/device-count env before the first backend touch
from difacto_tpu.analysis import core  # noqa: E402
from difacto_tpu.analysis.cli import DEFAULT_PATHS  # noqa: E402
from difacto_tpu.analysis.shardflow import get_shard_model  # noqa: E402
from difacto_tpu.utils import hloscan  # noqa: E402


def drive_scan(fs: int, capacity: int, budget: int,
               tau: int = 0) -> dict:
    """Compile the fs-sharded train step AND serve executor in-process
    under DIFACTO_HLOSCAN=1 and return the scan (hloscan.programs()).

    Must be called before anything imports jax: it forces
    JAX_PLATFORMS=cpu with enough virtual host devices for the mesh —
    the same harness the tier-1 fs-sharding tests run on."""
    os.environ["DIFACTO_HLOSCAN"] = "1"
    os.environ["DIFACTO_HLOSCAN_ROWS"] = str(capacity)
    if budget:
        os.environ["DIFACTO_HLOSCAN_BUDGET"] = str(budget)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{max(8, fs)}").strip()

    import numpy as np

    # train leg: the same fused step bench --multichip measures, one
    # leg at the requested fs (capacity.py scans it explicitly too)
    from difacto_tpu.parallel.capacity import (bounded_delay_report,
                                               capacity_scaling_report)
    capacity_scaling_report(fs_values=[fs], base_capacity=capacity // fs,
                            V_dim=4, batch=64, nnz_per_row=4, steps=1)

    # quantized-slot leg (ISSUE 19): the SAME fs-sharded step with the
    # int8 fused-row container — --check proves the dequant/requant
    # epilogues introduce no table-axis collective under fs sharding
    capacity_scaling_report(fs_values=[fs], base_capacity=capacity // fs,
                            V_dim=4, batch=64, nnz_per_row=4, steps=1,
                            slot_dtype="int8")

    if tau > 0:
        # bounded-delay leg: the SAME fs-sharded train step driven
        # through the real windowed pipeline (prefetch depth 2+τ) —
        # records per-τ scans under colon-free capacity.delay/* keys,
        # and --check still fails on any table-axis collective the
        # window might have introduced
        bounded_delay_report(hosts_values=(1,), taus=(tau,), fs=fs,
                             base_capacity=capacity // fs, V_dim=4,
                             batch=64, nnz_per_row=4, steps=2,
                             auc_legs=False)

    # serve leg: an fs-sharded read path through the real executor
    from difacto_tpu.data.rowblock import RowBlock
    from difacto_tpu.parallel import make_mesh
    from difacto_tpu.serve.executor import PredictExecutor
    from difacto_tpu.store.local import SlotStore
    from difacto_tpu.updaters.sgd_updater import SGDUpdaterParam

    mesh = make_mesh(dp=1, fs=fs) if fs > 1 else None
    param = SGDUpdaterParam(V_dim=4, hash_capacity=capacity,
                            V_threshold=0)
    store = SlotStore(param, mesh=mesh)
    rng = np.random.RandomState(0)
    keys = rng.randint(1, 1 << 62, 256).astype(np.uint64)
    store.push(keys, 1, np.ones(len(keys), np.float32))
    ex = PredictExecutor(store)
    nnz, batch = 4, 16
    blk = RowBlock(
        offset=np.arange(batch + 1, dtype=np.int64) * nnz,
        label=np.zeros(batch, np.float32),
        index=keys[rng.randint(0, len(keys), batch * nnz)],
        value=None)
    ex.predict(blk)
    assert ex.stats()["dispatches"] == 1
    return {"rows": capacity, "budget": budget,
            "programs": hloscan.programs()}


def build(root=".", dynamic=None) -> dict:
    """{'state_programs', 'pinning_builders', 'kernel_functions',
    'sites', 'programs', 'table_hits', 'budget_hits',
    'unknown_sites'} — everything the writers, the --check gate and
    the tier-1 test consume. ``dynamic`` is a scan dict (drive_scan or
    hloscan.load)."""
    root = Path(root).resolve()
    paths = [p for p in DEFAULT_PATHS if (root / p).exists()]
    project = core.Project(root, paths)
    model = get_shard_model(project)
    doc = model.to_json()
    out = {
        "state_programs": doc["state_programs"],
        "pinning_builders": doc["pinning_builders"],
        "kernel_functions": doc["kernel_functions"],
        "sites": doc["sites"],
        "programs": {},
        "table_hits": [],
        "budget_hits": [],
        "unknown_sites": [],
    }
    if dynamic:
        progs = dynamic["programs"]
        out["programs"] = {
            s: {"label": rec.get("label", ""),
                "table_collectives": rec.get("table_collectives", 0),
                "peak_temp_bytes": rec.get("peak_temp_bytes", 0),
                "over_budget": rec.get("over_budget", False),
                "signatures": rec.get("signatures", 0)}
            for s, rec in sorted(progs.items())}
        for v in hloscan.violations(progs):
            key = ("table_hits" if v["kind"] == "table-collective"
                   else "budget_hits")
            out[key].append(v)
        known = set(out["sites"])
        for site in sorted(progs):
            # a scan keyed by a real repo site must be a site the
            # static model discovered; non-site labels (explicit
            # record() keys) are exempt from the subset claim
            if ":" in site and site not in known:
                out["unknown_sites"].append(site)
    return out


def to_text(graph: dict) -> str:
    lines = []
    for sid, rec in sorted(graph["state_programs"].items()):
        mark = "PIN  " if rec["pinned"] else "LOOSE"
        lines.append(f"{mark} {sid}  jit({rec['target']}) "
                     f"pin={rec['pin']} donate={rec['donate_argnums']}")
    lines.append(f"pinning builders: "
                 f"{', '.join(graph['pinning_builders']) or '-'}")
    lines.append(f"pallas kernel functions: "
                 f"{len(graph['kernel_functions'])}")
    for site, rec in sorted(graph["programs"].items()):
        lines.append(
            f"scan {site}  {rec['label']}  "
            f"table_collectives={rec['table_collectives']} "
            f"peak_temp_bytes={rec['peak_temp_bytes']}"
            f"{'  OVER-BUDGET' if rec['over_budget'] else ''}")
    for key in ("table_hits", "budget_hits"):
        for v in graph[key]:
            lines.append(f"{key.upper().replace('_', '-')}: "
                         f"{v['site']}  {v['detail']}")
    for site in graph["unknown_sites"]:
        lines.append(f"UNKNOWN-SITES: {site}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="merged static+dynamic sharding map "
                    "(docs/static_analysis.md v5)")
    ap.add_argument("--root", default=".")
    ap.add_argument("--scan", action="store_true",
                    help="compile the fs train step + serve executor "
                         "in-process and scan their HLO (sets "
                         "JAX_PLATFORMS/XLA_FLAGS; do not import jax "
                         "before this)")
    ap.add_argument("--fs", type=int, default=4,
                    help="fs degree for --scan (default 4)")
    ap.add_argument("--tau", type=int, default=0,
                    help="bounded-delay window for an extra --scan leg "
                         "driving the windowed fs train step "
                         "(0 = skip)")
    ap.add_argument("--rows", type=int, default=4096,
                    help="table capacity for --scan legs (divisible "
                         "by fs; default 4096)")
    ap.add_argument("--budget", type=int,
                    default=256 * 1024 * 1024,
                    help="peak temp-arena budget in bytes for --scan "
                         "(default 256MiB; 0 disables)")
    ap.add_argument("--dynamic", default=None,
                    help="hloscan dump (DIFACTO_HLOSCAN_OUT) to merge")
    ap.add_argument("--json", default=None, help="write JSON here")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 on any table-axis collective, budget "
                         "breach, or dynamic site outside the static "
                         "model")
    args = ap.parse_args(argv)
    dynamic = None
    if args.scan:
        dynamic = drive_scan(args.fs, args.rows, args.budget, args.tau)
    elif args.dynamic:
        dynamic = hloscan.load(args.dynamic)
    graph = build(args.root, dynamic)
    if args.json:
        Path(args.json).write_text(
            json.dumps(graph, indent=1, sort_keys=True) + "\n",
            encoding="utf-8")
        print(f"hlomap: wrote {args.json}")
    print(to_text(graph))
    if args.check and (graph["table_hits"] or graph["budget_hits"]
                       or graph["unknown_sites"]):
        print("hlomap: CHECK FAILED — table-axis collective, temp "
              "budget breach, or scan site outside the static model",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
