#!/usr/bin/env python
"""Fleet operations CLI: rolling restarts, the routing tier, health,
router HA groups and elastic autoscaling.

Subcommands over one replica list (``--endpoints h1:p1,h2:p2``):

``roll``
    Health-gated rolling restart (difacto_tpu/serve/fleet.py): replace
    every replica one at a time — spawn successor on the shared
    SO_REUSEPORT port, wait for its ready file, ``#handoff``, verify —
    polling every replica's ``#health`` before and after each handoff.
    Any regression (not ready, queue-depth blowup, shed-rate spike,
    successor ready timeout) ABORTS the rollout with the current
    incumbent still serving. Prints one JSON report line.

        python tools/fleet.py roll --endpoints 127.0.0.1:9000,127.0.0.1:9001 \\
            --model /models/ctr_v2 --serve-arg serve_batch_size=256

``route``
    Start the thin router process (difacto_tpu/serve/router.py): speaks
    the same libsvm/control wire protocol, balances rows across the
    replicas with power-of-two-choices over live (in-flight, recent
    latency), retries an unanswered tail on a peer, serves aggregated
    ``#health``/``#stats``/``#metrics`` for the whole fleet, and shares
    endpoint ejections through ``--blacklist``.

        python tools/fleet.py route --endpoints 127.0.0.1:9000,127.0.0.1:9001 \\
            --port 9100 --blacklist /tmp/fleet.blacklist

``health``
    One gate pass over every replica; prints the regression (exit 1) or
    the all-healthy report (exit 0) — the preflight an operator runs
    before trusting a rollout to the gate.

``routers``
    Supervise an N-router SO_REUSEPORT group on ONE advertised port
    (``--port`` required, ``--n`` members): each member is a ``route``
    child with ``--takeover``, sharing ``--blacklist`` and
    ``--endpoints-file``; a member that dies is relaunched with
    launch.py's capped-exponential-backoff-plus-jitter schedule
    (``router_group_relaunches_total`` counts it, ``router_group_size``
    gauges the live group). Kill any member: the port keeps answering.

        python tools/fleet.py routers --n 2 --port 9100 \\
            --endpoints 127.0.0.1:9000,127.0.0.1:9001 \\
            --blacklist /tmp/fleet.blacklist

``scale``
    Run the elastic autoscaler (difacto_tpu/serve/autoscale.py): a
    hysteresis-damped control loop over the fleet's ``#health`` signals
    that spawns task=serve replicas into the routing ring under load
    (``#backends add`` nudge + ``--endpoints-file`` rewrite) and drains
    them back out when the load leaves.

        python tools/fleet.py scale --endpoints 127.0.0.1:9000 \\
            --model /models/ctr_v2 --router 127.0.0.1:9100 \\
            --min 1 --max 4 --endpoints-file /tmp/fleet.ring
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def cmd_roll(args) -> int:
    from difacto_tpu.serve.fleet import HealthGate, run_rolling_restart
    gate = HealthGate(queue_frac=args.queue_frac,
                      shed_spike=args.shed_spike)
    rep = run_rolling_restart(args.endpoints, model=args.model,
                              extra=args.serve_arg, wait_s=args.wait_s,
                              gate=gate)
    print(json.dumps(rep))
    return 0 if rep["ok"] else 1


def cmd_route(args) -> int:
    from difacto_tpu.serve.router import RouterServer
    router = RouterServer(args.endpoints, host=args.host, port=args.port,
                          chunk=args.chunk, retries=args.retries,
                          blacklist=args.blacklist or None,
                          takeover=args.takeover,
                          ready_file=args.ready_file,
                          balance=args.balance,
                          affinity_capacity=args.affinity_capacity,
                          endpoints_file=args.endpoints_file)
    router.start()
    if args.ready_file:
        with open(args.ready_file, "w") as f:
            f.write(f"{router.host} {router.port}\n")
    print(json.dumps({"router": f"{router.host}:{router.port}",
                      "endpoints": args.endpoints}), flush=True)
    try:
        router.wait(args.max_seconds or None)
    except KeyboardInterrupt:  # pragma: no cover - interactive exit
        pass
    finally:
        router.close()
    return 0


def cmd_health(args) -> int:
    from difacto_tpu.config import parse_endpoints
    from difacto_tpu.serve.fleet import HealthGate, fresh_health
    eps = parse_endpoints(args.endpoints)
    gate = HealthGate(queue_frac=args.queue_frac,
                      shed_spike=args.shed_spike)
    reason = gate.check(eps)
    replicas = []
    for host, port in eps:
        try:
            replicas.append(dict(fresh_health(host, port),
                                 endpoint=f"{host}:{port}"))
        except (OSError, ConnectionError, ValueError) as e:
            replicas.append({"endpoint": f"{host}:{port}",
                             "error": str(e)})
    print(json.dumps({"ok": reason is None, "reason": reason,
                      "replicas": replicas}))
    return 0 if reason is None else 1


def run_router_group(n, cmd_fn, max_seconds=0.0, poll_s=0.5,
                     backoff_base_s=1.0, sleep_fn=None,
                     max_relaunches=None, popen_fn=None):
    """Supervise ``n`` router children of one SO_REUSEPORT group.

    ``cmd_fn(i)`` returns the argv for member ``i``. While the loop
    runs, a member that exits — crash, OOM-kill, operator SIGKILL —
    is relaunched after launch.py's capped-exponential-backoff-plus-
    jitter delay (``relaunch_delay``): the attempt counter resets once
    the member is seen alive again, so a flapping member backs off
    while a one-off kill restarts fast. Because every member binds the
    same advertised port, the survivors keep answering the whole time;
    relaunch only restores capacity, never availability.

    Observable: ``router_group_relaunches_total`` counts every
    relaunch, ``router_group_size`` gauges the live member count.
    ``sleep_fn``/``popen_fn`` exist for tests (stub the clock and the
    spawn); ``max_relaunches`` bounds a runaway crash loop (None =
    unlimited). Runs until ``max_seconds`` (0 = until interrupted);
    children are terminated on the way out. Returns a report dict.
    """
    import subprocess
    import time

    from difacto_tpu.obs import REGISTRY
    from launch import relaunch_delay

    if sleep_fn is None:
        sleep_fn = time.sleep
    if popen_fn is None:
        popen_fn = subprocess.Popen
    relaunch_c = REGISTRY.counter(
        "router_group_relaunches_total",
        "dead router-group members relaunched by the supervisor")
    size_g = REGISTRY.gauge(
        "router_group_size",
        "live members of the SO_REUSEPORT router group")
    procs = [popen_fn(cmd_fn(i)) for i in range(n)]
    attempts = [0] * n
    relaunches = 0
    t0 = time.monotonic()
    try:
        while True:
            live = 0
            for i in range(n):
                if procs[i].poll() is None:
                    live += 1
                    attempts[i] = 0
                    continue
                if (max_relaunches is not None
                        and relaunches >= max_relaunches):
                    continue
                delay = relaunch_delay(attempts[i], backoff_base_s)
                log_rec = {"event": "router_relaunch", "member": i,
                           "attempt": attempts[i],
                           "delay_s": round(delay, 3),
                           "rc": procs[i].returncode}
                print(json.dumps(log_rec), flush=True)
                sleep_fn(delay)
                procs[i] = popen_fn(cmd_fn(i))
                attempts[i] += 1
                relaunches += 1
                relaunch_c.inc()
            size_g.set(float(live))
            if max_seconds and time.monotonic() - t0 >= max_seconds:
                break
            sleep_fn(poll_s)
    finally:
        for p in procs:
            if p.poll() is None:
                try:
                    p.terminate()
                except OSError:  # pragma: no cover - already gone
                    pass
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:  # pragma: no cover - stuck
                try:
                    p.kill()
                except OSError:
                    pass
    return {"ok": True, "members": n, "relaunches": relaunches}


def cmd_routers(args) -> int:
    if not args.port:
        print(json.dumps({"ok": False,
                          "reason": "routers needs an explicit --port "
                                    "(the group's one advertised port)"}))
        return 1

    def cmd_fn(i):
        argv = [sys.executable, os.path.abspath(__file__), "route",
                "--takeover",
                "--host", args.host, "--port", str(args.port),
                "--endpoints", args.endpoints,
                "--chunk", str(args.chunk),
                "--retries", str(args.retries),
                "--balance", args.balance,
                "--affinity-capacity", str(args.affinity_capacity)]
        if args.blacklist:
            argv += ["--blacklist", args.blacklist]
        if args.endpoints_file:
            argv += ["--endpoints-file", args.endpoints_file]
        if args.max_seconds:
            argv += ["--max-seconds", str(args.max_seconds)]
        return argv

    rep = run_router_group(args.n, cmd_fn, max_seconds=args.max_seconds,
                           backoff_base_s=args.backoff_s,
                           max_relaunches=args.max_relaunches)
    print(json.dumps(rep))
    return 0 if rep["ok"] else 1


def cmd_scale(args) -> int:
    import socket
    import tempfile

    from difacto_tpu.config import parse_endpoints
    from difacto_tpu.serve import fleet as fleet_ops
    from difacto_tpu.serve.autoscale import Autoscaler

    def spawn_fn(idx):
        # ephemeral port chosen here (not 0) so the endpoint is known
        # before the child answers; the ready-file wait closes the race
        with socket.socket() as s:
            s.bind((args.spawn_host, 0))
            port = s.getsockname()[1]
        fd, ready = tempfile.mkstemp(suffix=f".scale{idx}.ready")
        os.close(fd)
        os.unlink(ready)
        proc = fleet_ops.spawn_successor(args.model, port, ready,
                                         extra=args.serve_arg,
                                         host=args.spawn_host)
        # raises on child exit or timeout -> the autoscaler counts an
        # abort and keeps measuring (autoscale.py _scale_up)
        fleet_ops._wait_ready_file(ready, proc, args.wait_s, 0.05)
        return (args.spawn_host, port)

    router = None
    if args.router:
        router = parse_endpoints(args.router)[0]
    scaler = Autoscaler(
        args.endpoints, spawn_fn, router=router,
        min_replicas=args.min, max_replicas=args.max,
        poll_s=args.poll_s,
        up_queue_frac=args.up_queue_frac, up_shed_rate=args.up_shed_rate,
        down_queue_frac=args.down_queue_frac,
        up_ticks=args.up_ticks, down_ticks=args.down_ticks,
        cooldown_s=args.cooldown_s,
        endpoints_file=args.endpoints_file)
    try:
        rep = scaler.run(args.max_seconds or None)
    except KeyboardInterrupt:  # pragma: no cover - interactive exit
        rep = {"ok": True, "interrupted": True, "events": scaler.events}
    finally:
        scaler.close()
    print(json.dumps(rep))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    sub = ap.add_subparsers(dest="cmd", required=True)

    common = argparse.ArgumentParser(add_help=False)
    common.add_argument("--endpoints", required=True,
                        help="replica list, h1:p1,h2:p2")
    common.add_argument("--queue-frac", type=float, default=0.9,
                        help="gate: abort past this fraction of a "
                             "replica's queue_cap")
    common.add_argument("--shed-spike", type=float, default=0.25,
                        help="gate: abort when shed_rate rises this much "
                             "over the rollout-start baseline")

    roll = sub.add_parser("roll", parents=[common],
                          help="health-gated rolling restart")
    roll.add_argument("--model", required=True,
                      help="model_in for the successor processes")
    roll.add_argument("--serve-arg", action="append", default=[],
                      help="extra k=v for successors (repeatable)")
    roll.add_argument("--wait-s", type=float, default=180.0)
    roll.set_defaults(fn=cmd_roll)

    routing = argparse.ArgumentParser(add_help=False)
    routing.add_argument("--host", default="127.0.0.1")
    routing.add_argument("--port", type=int, default=0)
    routing.add_argument("--chunk", type=int, default=64,
                         help="max rows pipelined per backend forward")
    routing.add_argument("--retries", type=int, default=2,
                         help="per-backend retry budget per forward")
    routing.add_argument("--blacklist", default="",
                         help="shared endpoint-health file "
                              "(serve/fleethealth.py)")
    routing.add_argument("--balance", default="p2c",
                         choices=("p2c", "affinity"),
                         help="p2c = power-of-two-choices; affinity = "
                              "consistent-hash rows to the replica whose "
                              "fs-shard owns their keys (p2c fallback "
                              "when the owner is ejected)")
    routing.add_argument("--affinity-capacity", type=int, default=0,
                         help="the model's hash_capacity, so the "
                              "affinity ring mirrors fs_shard_bounds "
                              "(0 = plain key hashing)")
    routing.add_argument("--endpoints-file", default="",
                         help="durable membership: whitespace-separated "
                              "h:p list re-read on (mtime,size) change "
                              "(the autoscaler rewrites it)")
    routing.add_argument("--max-seconds", type=float, default=0.0)

    route = sub.add_parser("route", parents=[common, routing],
                           help="start one router process")
    route.add_argument("--takeover", action="store_true",
                       help="bind SO_REUSEPORT so group members / a "
                            "successor can share the port")
    route.add_argument("--ready-file", default="",
                       help="write 'host port' here once listening")
    route.set_defaults(fn=cmd_route)

    routers = sub.add_parser("routers", parents=[common, routing],
                             help="supervise an N-router SO_REUSEPORT "
                                  "group with relaunch-on-death")
    routers.add_argument("--n", type=int, default=2,
                         help="group size (members on the one port)")
    routers.add_argument("--backoff-s", type=float, default=1.0,
                         help="relaunch backoff base (doubles per "
                              "consecutive death, capped, jittered)")
    routers.add_argument("--max-relaunches", type=int, default=None,
                         help="stop relaunching after this many "
                              "(default: unlimited)")
    routers.set_defaults(fn=cmd_routers)

    scale = sub.add_parser("scale", parents=[common],
                           help="run the elastic autoscaler")
    scale.add_argument("--model", required=True,
                       help="model_in for scale-up replicas")
    scale.add_argument("--serve-arg", action="append", default=[],
                       help="extra k=v for spawned replicas (repeatable)")
    scale.add_argument("--router", default="",
                       help="router h:p to nudge with '#backends "
                            "add|remove' on every decision")
    scale.add_argument("--endpoints-file", default="",
                       help="rewritten atomically on every decision "
                            "(the routers' durable membership)")
    scale.add_argument("--spawn-host", default="127.0.0.1")
    scale.add_argument("--min", type=int, default=1)
    scale.add_argument("--max", type=int, default=8)
    scale.add_argument("--poll-s", type=float, default=0.5)
    scale.add_argument("--up-queue-frac", type=float, default=0.6)
    scale.add_argument("--up-shed-rate", type=float, default=0.02)
    scale.add_argument("--down-queue-frac", type=float, default=0.1)
    scale.add_argument("--up-ticks", type=int, default=2)
    scale.add_argument("--down-ticks", type=int, default=6)
    scale.add_argument("--cooldown-s", type=float, default=5.0)
    scale.add_argument("--wait-s", type=float, default=180.0,
                       help="ready-file wait for a spawned replica")
    scale.add_argument("--max-seconds", type=float, default=0.0)
    scale.set_defaults(fn=cmd_scale)

    health = sub.add_parser("health", parents=[common],
                            help="one gate pass over the fleet")
    health.set_defaults(fn=cmd_health)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
