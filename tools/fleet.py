#!/usr/bin/env python
"""Fleet operations CLI: rolling restarts, the routing tier, health.

Three subcommands over one replica list (``--endpoints h1:p1,h2:p2``):

``roll``
    Health-gated rolling restart (difacto_tpu/serve/fleet.py): replace
    every replica one at a time — spawn successor on the shared
    SO_REUSEPORT port, wait for its ready file, ``#handoff``, verify —
    polling every replica's ``#health`` before and after each handoff.
    Any regression (not ready, queue-depth blowup, shed-rate spike,
    successor ready timeout) ABORTS the rollout with the current
    incumbent still serving. Prints one JSON report line.

        python tools/fleet.py roll --endpoints 127.0.0.1:9000,127.0.0.1:9001 \\
            --model /models/ctr_v2 --serve-arg serve_batch_size=256

``route``
    Start the thin router process (difacto_tpu/serve/router.py): speaks
    the same libsvm/control wire protocol, balances rows across the
    replicas with power-of-two-choices over live (in-flight, recent
    latency), retries an unanswered tail on a peer, serves aggregated
    ``#health``/``#stats``/``#metrics`` for the whole fleet, and shares
    endpoint ejections through ``--blacklist``.

        python tools/fleet.py route --endpoints 127.0.0.1:9000,127.0.0.1:9001 \\
            --port 9100 --blacklist /tmp/fleet.blacklist

``health``
    One gate pass over every replica; prints the regression (exit 1) or
    the all-healthy report (exit 0) — the preflight an operator runs
    before trusting a rollout to the gate.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def cmd_roll(args) -> int:
    from difacto_tpu.serve.fleet import HealthGate, run_rolling_restart
    gate = HealthGate(queue_frac=args.queue_frac,
                      shed_spike=args.shed_spike)
    rep = run_rolling_restart(args.endpoints, model=args.model,
                              extra=args.serve_arg, wait_s=args.wait_s,
                              gate=gate)
    print(json.dumps(rep))
    return 0 if rep["ok"] else 1


def cmd_route(args) -> int:
    from difacto_tpu.serve.router import RouterServer
    router = RouterServer(args.endpoints, host=args.host, port=args.port,
                          chunk=args.chunk, retries=args.retries,
                          blacklist=args.blacklist or None)
    router.start()
    if args.ready_file:
        with open(args.ready_file, "w") as f:
            f.write(f"{router.host} {router.port}\n")
    print(json.dumps({"router": f"{router.host}:{router.port}",
                      "endpoints": args.endpoints}), flush=True)
    try:
        router.wait(args.max_seconds or None)
    except KeyboardInterrupt:  # pragma: no cover - interactive exit
        pass
    finally:
        router.close()
    return 0


def cmd_health(args) -> int:
    from difacto_tpu.config import parse_endpoints
    from difacto_tpu.serve.fleet import HealthGate, fresh_health
    eps = parse_endpoints(args.endpoints)
    gate = HealthGate(queue_frac=args.queue_frac,
                      shed_spike=args.shed_spike)
    reason = gate.check(eps)
    replicas = []
    for host, port in eps:
        try:
            replicas.append(dict(fresh_health(host, port),
                                 endpoint=f"{host}:{port}"))
        except (OSError, ConnectionError, ValueError) as e:
            replicas.append({"endpoint": f"{host}:{port}",
                             "error": str(e)})
    print(json.dumps({"ok": reason is None, "reason": reason,
                      "replicas": replicas}))
    return 0 if reason is None else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    sub = ap.add_subparsers(dest="cmd", required=True)

    common = argparse.ArgumentParser(add_help=False)
    common.add_argument("--endpoints", required=True,
                        help="replica list, h1:p1,h2:p2")
    common.add_argument("--queue-frac", type=float, default=0.9,
                        help="gate: abort past this fraction of a "
                             "replica's queue_cap")
    common.add_argument("--shed-spike", type=float, default=0.25,
                        help="gate: abort when shed_rate rises this much "
                             "over the rollout-start baseline")

    roll = sub.add_parser("roll", parents=[common],
                          help="health-gated rolling restart")
    roll.add_argument("--model", required=True,
                      help="model_in for the successor processes")
    roll.add_argument("--serve-arg", action="append", default=[],
                      help="extra k=v for successors (repeatable)")
    roll.add_argument("--wait-s", type=float, default=180.0)
    roll.set_defaults(fn=cmd_roll)

    route = sub.add_parser("route", parents=[common],
                           help="start the routing tier")
    route.add_argument("--host", default="127.0.0.1")
    route.add_argument("--port", type=int, default=0)
    route.add_argument("--chunk", type=int, default=64,
                       help="max rows pipelined per backend forward")
    route.add_argument("--retries", type=int, default=2,
                       help="per-backend retry budget per forward")
    route.add_argument("--blacklist", default="",
                       help="shared endpoint-health file "
                            "(serve/fleethealth.py)")
    route.add_argument("--ready-file", default="",
                       help="write 'host port' here once listening")
    route.add_argument("--max-seconds", type=float, default=0.0)
    route.set_defaults(fn=cmd_route)

    health = sub.add_parser("health", parents=[common],
                            help="one gate pass over the fleet")
    health.set_defaults(fn=cmd_health)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
