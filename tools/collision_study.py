"""Hashing-trick quality study: planted-model AUC vs hash-table load factor.

Round-4 verdict missing #1: the reference's distributed SGD keys the model
by exact 64-bit feature id (servers grow unordered_maps unboundedly,
src/sgd/sgd_updater.h:141-176), so distinct features never alias; this
framework's multi-host SGD uses the fixed-capacity hashed store, where
distinct ids can permanently share a row. This study makes that tradeoff a
NUMBER: train the criteo stand-in FM at hash_capacity in {2x, 1x, 0.5x,
0.25x} the measured distinct-feature count and report best validation AUC
alongside the analytic collision fraction (store.local.collision_stats).

Usage: python tools/collision_study.py [--rows N] [--data-dir data]
Writes one JSON line per capacity; reuses data/criteo_*.rec if present.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import numpy as np  # noqa: E402


def ensure_data(data_dir: str, rows: int, batch: int) -> tuple:
    from difacto_tpu.data.converter import Converter
    from tools.download import synth_criteo

    train_txt = os.path.join(data_dir, "criteo_train.txt")
    if not os.path.exists(train_txt):
        os.makedirs(data_dir, exist_ok=True)
        synth_criteo(data_dir, rows=rows)
    recs = []
    for split in ("train", "val"):
        txt = os.path.join(data_dir, f"criteo_{split}.txt")
        rec = os.path.join(data_dir, f"criteo_{split}.rec")
        if not os.path.exists(rec):
            conv = Converter()
            conv.init([("data_in", txt), ("data_format", "criteo"),
                       ("data_out", rec), ("data_out_format", "rec"),
                       ("rec_batch_size", str(batch))])
            conv.run()
        recs.append(rec)
    return tuple(recs)


def count_distinct(rec_path: str) -> np.ndarray:
    """All distinct raw feature ids in the file (chunked union)."""
    from difacto_tpu.data import Reader
    uniqs = []
    for blk in Reader(rec_path, "rec", 0, 1):
        uniqs.append(np.unique(blk.index))
        if len(uniqs) >= 16:
            uniqs = [np.unique(np.concatenate(uniqs))]
    return np.unique(np.concatenate(uniqs))


def run_one(train_rec: str, val_rec: str, capacity: int, epochs: int,
            batch: int) -> dict:
    from difacto_tpu.learners import Learner
    ln = Learner.create("sgd")
    ln.init([("data_in", train_rec), ("data_val", val_rec),
             ("data_format", "rec"), ("loss", "fm"), ("V_dim", "16"),
             ("V_threshold", "25"), ("lr", "0.02"), ("V_lr", "0.02"),
             ("l1", "1e-4"), ("l2", "1e-3"), ("V_l2", "2e-3"),
             ("batch_size", str(batch)), ("shuffle", "1"),
             ("max_num_epochs", str(epochs)),
             ("report_interval", "0"), ("stop_rel_objv", "0"),
             ("stop_val_auc", "-2"), ("V_dtype", "bfloat16"),
             ("hash_capacity", str(capacity))])
    aucs = []
    ln.add_epoch_end_callback(
        lambda e, t, v: aucs.append(v.auc / max(v.nrows, 1.0)))
    t0 = time.perf_counter()
    ln.run()
    return {"val_auc_best": round(max(aucs), 4),
            "val_auc_by_epoch": [round(a, 4) for a in aucs],
            "wall_s": round(time.perf_counter() - t0, 1)}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=2_000_000)
    ap.add_argument("--data-dir", default="data")
    ap.add_argument("--batch", type=int, default=32768)
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--mults", default="2,1,0.5,0.25",
                    help="capacity multipliers over the distinct-id count")
    args = ap.parse_args()

    from difacto_tpu.store.local import collision_stats

    train_rec, val_rec = ensure_data(args.data_dir, args.rows, args.batch)
    ids = count_distinct(train_rec)
    n = len(ids)
    print(json.dumps({"distinct_ids": n, "rows": args.rows}), flush=True)

    for mult in (float(m) for m in args.mults.split(",")):
        cap = int(n * mult)
        stats = collision_stats(ids, cap)
        res = run_one(train_rec, val_rec, cap, args.epochs, args.batch)
        print(json.dumps({"capacity_mult": mult, **stats, **res}),
              flush=True)


if __name__ == "__main__":
    main()
