"""Probe: should STREAMED (cache-less) panel training chunk on device?

Staged runs build the chunked-run backward layout once at staging time
and replay it (docs/perf_notes.md "the chunked backward"). Streamed runs
currently dispatch the unsorted-scatter backward — the round-4 note
("a per-batch per-epoch argsort would eat the win") was measured for the
HOST-side sort in the old sorted-backward era. This probe times one mode
per process (fresh chip state; pass --mode):

  chunked  : host-prechunked batches + chunked step (the replay ceiling)
  unsorted : plain panel batches + unsorted-scatter backward (streaming
             today)
  devchunk : plain panel batches; each step first runs the jitted
             panel_chunk_tokens on device, then the chunked step (what a
             streamed run COULD do with zero host cost)

Usage: python tools/probe_stream_chunk.py --mode devchunk [--vdim 64]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import numpy as np  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=("chunked", "unsorted", "devchunk"),
                    required=True)
    ap.add_argument("--vdim", type=int, default=64)
    ap.add_argument("--batch", type=int, default=65536)
    ap.add_argument("--uniq", type=int, default=1 << 17)
    ap.add_argument("--capacity", type=int, default=1 << 21)
    ap.add_argument("--steps", type=int, default=30)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from bench import build_step, make_batches
    from difacto_tpu.ops.batch import panel_chunk_tokens

    step_raw, state = build_step(args.vdim, args.capacity,
                                 "bfloat16")[:2]
    hb = make_batches(4, args.batch, 39, args.uniq, args.capacity, "zipf")
    u_cap = int(hb[0][1].shape[0])
    chunker = jax.jit(panel_chunk_tokens, static_argnums=(1,))
    batches = []
    for b, s in hb:
        bd = jax.device_put(b)
        if args.mode != "chunked":
            bd = bd._replace(chunk_idx=None, chunk_lane=None,
                             chunk_vals=None)
        batches.append((bd, jnp.asarray(s)))
    step = jax.jit(step_raw, donate_argnums=0)

    def one(state, i):
        b, s = batches[i % 4]
        if args.mode == "devchunk":
            # lint: ok(jax-recompile) u_cap is fixed for the probe's
            # lifetime (derived once from the generated batch set)
            b = chunker(b, u_cap)
        return step(state, b, s)

    state, objv, _ = one(state, 0)
    # lint: ok(jax-host-sync) completion fence of the timing harness
    float(objv)  # compile + warm
    t0 = time.perf_counter()
    for i in range(args.steps):
        state, objv, _ = one(state, i)
    # lint: ok(jax-host-sync) completion fence of the timing harness
    float(objv)
    dt = (time.perf_counter() - t0) / args.steps
    print(json.dumps({"mode": args.mode, "V": args.vdim, "B": args.batch,
                      "u_cap": u_cap, "ms": round(dt * 1e3, 1),
                      "eps": round(args.batch / dt)}))


if __name__ == "__main__":
    main()
