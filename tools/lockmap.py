#!/usr/bin/env python
"""Merged static + dynamic lock-order graph — `make lockmap` runs this.

The static half is the interprocedural concurrency model difacto-lint
builds (difacto_tpu/analysis/concurrency.py): every lock in the tree and
every acquisition-order edge the call graph can prove. The dynamic half
is an optional locktrace dump (DIFACTO_LOCKTRACE=1 + either
DIFACTO_LOCKTRACE_OUT=<path> or locktrace.dump()): the edges real
executions actually took. Merging them answers two questions the halves
cannot answer alone:

- which static edges are CONFIRMED by a real run (solid, bold in DOT)
  versus predicted-only (the static model covers paths tests never
  execute — that is its job);
- whether any observed edge is MISSING from the static graph
  (``dynamic_only`` — a callgraph blind spot; the tier-1 gate in
  tests/test_lint.py fails on these so they get fixed, but lockmap
  shows them to humans too).

Usage:
  python tools/lockmap.py [--dynamic trace.json] [--dot lockmap.dot]
                          [--json lockmap.json] [--check]

``--check`` exits 1 when the static graph has a cycle or a dynamic edge
escapes it (CI-able); the default is informational (exit 0).
"""

from __future__ import annotations

import argparse
import os
import sys
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from difacto_tpu.analysis import core  # noqa: E402
from difacto_tpu.analysis.cli import DEFAULT_PATHS  # noqa: E402
from difacto_tpu.analysis.concurrency import get_model  # noqa: E402
from difacto_tpu.analysis.races import get_race_model  # noqa: E402
from difacto_tpu.utils import locktrace  # noqa: E402


def build(root=".", dynamic_path=None):
    """{'locks', 'static_edges', 'dynamic_edges', 'confirmed',
    'dynamic_only', 'guarded_by', 'cycles'} — everything the DOT/JSON
    writers and the tier-1 gate consume."""
    root = Path(root).resolve()
    paths = [p for p in DEFAULT_PATHS if (root / p).exists()]
    project = core.Project(root, paths)
    model = get_model(project)
    races = get_race_model(project)
    site2lock = {f"{li.path}:{li.line}": lid
                 for lid, li in model.locks.items()}
    dynamic_edges = {}
    unknown_sites = []
    if dynamic_path:
        data = locktrace.load(dynamic_path)
        for (a, b), n in sorted(data["edges"].items()):
            la, lb = site2lock.get(a), site2lock.get(b)
            if la is None or lb is None:
                unknown_sites.append([a, b])
                continue
            dynamic_edges[(la, lb)] = dynamic_edges.get((la, lb), 0) + n
    static = set(model.edges)
    dynamic = set(dynamic_edges)
    # invert the race pass's GuardedBy facts: lock -> fields it guards,
    # so the lock graph shows WHAT each lock protects, not just its
    # ordering constraints
    guards: dict = {}
    for fid, locks in sorted(races.guarded_by.items()):
        for lk in locks:
            guards.setdefault(lk, []).append(fid)
    return {
        "model": model,
        "locks": model.locks,
        "static_edges": model.edges,
        "dynamic_edges": dynamic_edges,
        "confirmed": sorted(static & dynamic),
        "dynamic_only": sorted(dynamic - static),
        "unknown_sites": unknown_sites,
        "guarded_by": {fid: list(locks)
                       for fid, locks in sorted(
                           races.guarded_by.items())},
        "guards": guards,
        "cycles": model.cycles,
    }


def to_dot(graph) -> str:
    out = ["digraph lockmap {",
           '  rankdir=LR; node [shape=box, fontsize=10];']
    confirmed = set(graph["confirmed"])
    dyn_only = set(graph["dynamic_only"])
    for lid, li in sorted(graph["locks"].items()):
        label = lid.replace("::", "\\n")
        guarded = graph["guards"].get(lid, [])
        if guarded:
            # what the lock protects (race-pass GuardedBy inference)
            shown = [f.rpartition("::")[2] for f in guarded[:6]]
            if len(guarded) > 6:
                shown.append(f"+{len(guarded) - 6} more")
            label += "\\nguards: " + ", ".join(shown)
        out.append(f'  "{lid}" [label="{label}\\n[{li.kind}]"];')
    for (a, b), e in sorted(graph["static_edges"].items()):
        style = ('color=black, penwidth=2.2, label="confirmed"'
                 if (a, b) in confirmed else "color=gray50")
        out.append(f'  "{a}" -> "{b}" [{style}];')
    for (a, b) in sorted(dyn_only):
        out.append(f'  "{a}" -> "{b}" [color=red, style=dashed, '
                   f'label="dynamic only"];')
    out.append("}")
    return "\n".join(out) + "\n"


def to_json(graph) -> dict:
    doc = graph["model"].to_json()
    doc["dynamic_edges"] = [{"src": a, "dst": b, "count": n}
                            for (a, b), n in
                            sorted(graph["dynamic_edges"].items())]
    doc["confirmed"] = [list(e) for e in graph["confirmed"]]
    doc["dynamic_only"] = [list(e) for e in graph["dynamic_only"]]
    doc["unknown_sites"] = graph["unknown_sites"]
    doc["guarded_by"] = graph["guarded_by"]
    doc["guards"] = graph["guards"]
    return doc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="lockmap", description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=".")
    ap.add_argument("--dynamic", default=None,
                    help="locktrace JSON dump (DIFACTO_LOCKTRACE_OUT)")
    ap.add_argument("--dot", default=None, help="write DOT here")
    ap.add_argument("--json", default=None, help="write JSON here")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 on a static cycle or a dynamic edge "
                         "outside the static graph")
    args = ap.parse_args(argv)
    graph = build(args.root, args.dynamic)
    if args.dot:
        Path(args.dot).write_text(to_dot(graph), encoding="utf-8")
        print(f"lockmap: wrote {args.dot}")
    if args.json:
        import json as _json
        Path(args.json).write_text(
            _json.dumps(to_json(graph), indent=1, sort_keys=True) + "\n",
            encoding="utf-8")
        print(f"lockmap: wrote {args.json}")
    n_static = len(graph["static_edges"])
    print(f"lockmap: {len(graph['locks'])} locks, {n_static} static "
          f"edges, {len(graph['dynamic_edges'])} dynamic edges "
          f"({len(graph['confirmed'])} confirmed, "
          f"{len(graph['dynamic_only'])} dynamic-only), "
          f"{len(graph['guarded_by'])} GuardedBy fields, "
          f"{len(graph['cycles'])} cycle(s)")
    for cyc in graph["cycles"]:
        print(f"lockmap: CYCLE {' -> '.join(cyc)} -> {cyc[0]}")
    for a, b in graph["dynamic_only"]:
        print(f"lockmap: DYNAMIC-ONLY {a} -> {b} (static model blind "
              f"spot — fix the callgraph heuristics)")
    if args.check and (graph["cycles"] or graph["dynamic_only"]):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
