"""Probe: where does the STREAMED (non-replay) epoch go?

Round-4 verdict weak #2: the 1TB north-star config cannot replay from HBM,
so every epoch at that scale is the streamed path — yet only the replay
regime had numbers. This probe decomposes a streamed epoch on the real
chip into its pipeline stages:

  host-pack : producer threads parse rec members -> localize -> panel pack
  transfer  : host->device staging of the packed buffers (jnp.asarray)
  step      : the fused train step itself (replay rate, no transfers)
  streamed  : the full pipeline with device_cache_mb=0, BOTH producer
              transports (thread vs process + shared-memory ring) with
              the learner's per-stage decomposition, so the thread-vs-
              process overlap is measured, not inferred
  replay    : the same run with the cache on (epochs 1+ replay from HBM)

Usage: python tools/probe_stream.py [--rows N] [--vdim K] [--batch B]
"""
from __future__ import annotations

import argparse
import json
import tempfile
import time


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=600_000)
    ap.add_argument("--vdim", type=int, default=16)
    ap.add_argument("--batch", type=int, default=32768)
    ap.add_argument("--capacity", type=int, default=1 << 21)
    ap.add_argument("--epochs", type=int, default=3)
    args = ap.parse_args()

    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    import jax
    import jax.numpy as jnp
    import numpy as np

    from bench import _gen_criteo_text
    from difacto_tpu.data.converter import Converter
    from difacto_tpu.learners import Learner

    out = {"rows": args.rows, "vdim": args.vdim, "batch": args.batch}

    with tempfile.TemporaryDirectory() as d:
        path = f"{d}/criteo.txt"
        _gen_criteo_text(path, args.rows)
        conv = Converter()
        conv.init([("data_in", path), ("data_format", "criteo"),
                   ("data_out", f"{d}/criteo.rec"),
                   ("data_out_format", "rec"),
                   ("rec_batch_size", str(args.batch))])
        conv.run()

        def make_learner(cache_mb: int,
                         producer_mode: str = "thread") -> Learner:
            ln = Learner.create("sgd")
            ln.init([("data_in", f"{d}/criteo.rec"), ("data_format", "rec"),
                     ("loss", "fm"), ("V_dim", str(args.vdim)),
                     ("V_threshold", "0"), ("lr", "0.1"), ("l1", "1e-4"),
                     ("batch_size", str(args.batch)), ("shuffle", "0"),
                     ("max_num_epochs", str(args.epochs)),
                     ("num_jobs_per_epoch", "1"),
                     ("report_interval", "0"), ("stop_rel_objv", "0"),
                     ("V_dtype", "bfloat16"),
                     ("device_cache_mb", str(cache_mb)),
                     ("producer_mode", producer_mode),
                     ("hash_capacity", str(args.capacity))])
            return ln

        # -------------------------------------------------- host-pack only
        # a THROWAWAY learner: _prepare_from_uniq records caps in the
        # learner's sticky shape schedule, and feeding it off-path caps
        # would force extra jit variants on a learner that later trains
        # (measured: a polluted schedule added a ~50 s compile to epoch 1)
        ln_pack = make_learner(0)
        from difacto_tpu.data.cached import CachedBatchReader
        from difacto_tpu.ops.batch import bucket
        uri = ln_pack._cached_uri(3)  # K_TRAINING
        b_cap_train = bucket(args.batch, 8)
        n_items = 0
        payload_bytes = 0
        payloads = []
        t0 = time.perf_counter()
        rdr = CachedBatchReader(uri, 0, 1, args.batch, shuffle=False,
                                neg_sampling=1.0, seed=0, need_counts=True)
        for sub, uniq, cnts in rdr:
            kind, blk, payload = ("ready", sub, ln_pack._prepare_from_uniq(
                sub, uniq, cnts, True, True, 8, "train",
                b_cap_train))
            n_items += 1
            layout, i32, f32, binary, b_cap, d2, u_cap = payload
            payload_bytes += i32.nbytes + f32.nbytes
            if len(payloads) < 4:
                payloads.append((i32, f32))
        t_pack = time.perf_counter() - t0
        out["host_pack"] = {
            "sec_per_epoch": round(t_pack, 2),
            "examples_per_sec": round(args.rows / t_pack, 1),
            "batches": n_items,
            "payload_mb_per_epoch": round(payload_bytes / 2**20, 1),
        }

        # -------------------------------------------------- transfer only
        # stage the first payloads repeatedly to measure sustained
        # host->device bandwidth through this link
        reps = max(1, n_items // len(payloads))
        moved = sum(i.nbytes + f.nbytes for i, f in payloads) * reps
        t0 = time.perf_counter()
        last = None
        for _ in range(reps):
            for i32, f32 in payloads:
                a = jnp.asarray(i32)
                b = jnp.asarray(f32)
                last = (a, b)
        jax.block_until_ready(last)
        t_xfer = time.perf_counter() - t0
        out["transfer"] = {
            "sec_per_epoch_equiv": round(t_xfer, 2),
            "mb_per_sec": round(moved / 2**20 / t_xfer, 1),
        }

        # -------------------------------------------------- streamed e2e
        # both producer transports, so the thread-vs-process overlap is a
        # measured table (docs/perf_notes.md "The streamed regime"), each
        # with the learner's pack/transfer/step second totals attached
        def streamed_run(mode: str) -> dict:
            ln = make_learner(0, producer_mode=mode)
            marks = []
            ln.add_epoch_end_callback(
                lambda e, t, v: marks.append(time.perf_counter()))
            t0 = time.perf_counter()
            ln.run()
            epochs_s = np.diff([t0] + marks)
            return {
                "epoch_sec": [round(s, 2) for s in epochs_s],
                "steady_examples_per_sec": round(
                    args.rows / float(np.mean(epochs_s[1:])), 1),
                "stages": ln.stage_stats(),
            }

        out["streamed"] = streamed_run("thread")
        out["streamed_process"] = streamed_run("process")

        # -------------------------------------------------- replay e2e
        ln2 = make_learner(2048)
        marks2 = []
        ln2.add_epoch_end_callback(
            lambda e, t, v: marks2.append(time.perf_counter()))
        t0 = time.perf_counter()
        ln2.run()
        epochs2_s = np.diff([t0] + marks2)
        out["replay"] = {
            "epoch_sec": [round(s, 2) for s in epochs2_s],
            "steady_examples_per_sec": round(
                args.rows / float(np.mean(epochs2_s[1:])), 1),
        }

    print(json.dumps(out))


if __name__ == "__main__":
    main()
