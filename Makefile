# Local CI gate — the same checks .github/workflows/ci.yml runs.
# (Reference analog: Makefile `make test` + .travis.yml.)
#
#   make test   - full pytest suite on a virtual 8-device CPU mesh
#   make smoke  - bench.py + driver entry smoke (catches broken artifacts)
#   make ci     - both

PY ?= python
# obs-report inputs: the metrics JSONL a run wrote (metrics_path knob)
# and optionally its Chrome trace (DIFACTO_TRACE)
METRICS ?= run.metrics.jsonl
TRACE ?=
# convert inputs (make convert): text in -> rec2 cache out
DATA_IN ?= data.txt
DATA_FORMAT ?= criteo
DATA_OUT ?= $(basename $(DATA_IN)).rec

.PHONY: test smoke ci lint lint-changed lint-baseline lockmap jitmap \
	hlomap chaos fleet-chaos online-chaos durability-chaos obs-report \
	convert stream-bench multichip-bench kernel-parity online-bench \
	capacity-bench durability-bench

test:
	$(PY) -m pytest tests/ -x -q

# difacto-lint (docs/static_analysis.md): compileall as a cheap syntax
# pass, then the AST analyzer — concurrency/JAX/registry-drift rules
# over difacto_tpu/, tools/, launch.py, bench.py. Exit 0 = no
# unsuppressed, non-baselined findings. LINT_FORMAT=github emits PR
# annotations (ci.yml uses it).
LINT_FORMAT ?= text
lint:
	$(PY) -m compileall -q difacto_tpu tests tools bench.py launch.py
	$(PY) tools/lint.py --format=$(LINT_FORMAT)

# fast local loop: local rules only on files changed vs the merge-base
# (worktree edits + untracked included); cross-file and concurrency
# rules still see the whole tree — their findings can live in files the
# change never touched
lint-changed:
	$(PY) tools/lint.py --changed-only --format=$(LINT_FORMAT)

# regenerate the grandfathered-finding baseline INTENTIONALLY (e.g.
# after adding a rule that flags pre-existing code you are not fixing
# in the same change) — never to silence a finding you just introduced
lint-baseline:
	$(PY) tools/lint.py --write-baseline

# merged static+dynamic lock-order graph, with each lock labeled by the
# fields the race pass proves it guards (docs/static_analysis.md):
#   make lockmap                          # static model only
#   make lockmap LOCKTRACE=run.locks.json # + a DIFACTO_LOCKTRACE_OUT dump
LOCKTRACE ?=
lockmap:
	$(PY) tools/lockmap.py --dot lockmap.dot --json lockmap.json \
	  $(if $(LOCKTRACE),--dynamic $(LOCKTRACE))

# merged static+dynamic jit-program map: every jit site with its
# compile-key verdict, plus a real run's per-site compile counts and
# fetch points (docs/static_analysis.md v4):
#   make jitmap                            # static model only
#   make jitmap JAXTRACE=run.jax.json      # + a DIFACTO_JAXTRACE_OUT dump
JAXTRACE ?=
jitmap:
	$(PY) tools/jitmap.py --json jitmap.json \
	  $(if $(JAXTRACE),--dynamic $(JAXTRACE))

# merged static+dynamic sharding map (docs/static_analysis.md v5): the
# shardflow layout-pin verdicts next to a compiled-HLO collective/
# memory scan of the REAL fs=4 train step + serve executor on the CPU
# virtual mesh, plus a bounded-delay leg (--tau 4) driving the windowed
# fs=4 train step through the 2+τ pipeline. --check fails on any
# table-axis all-gather/all-to-all, temp-budget breach, or scan site
# outside the static model:
#   make hlomap                            # scan + merge + gate
#   make hlomap HLOSCAN=run.hlo.json       # merge a DIFACTO_HLOSCAN_OUT dump
HLOSCAN ?=
hlomap:
	$(PY) tools/hlomap.py --json hlomap.json \
	  $(if $(HLOSCAN),--dynamic $(HLOSCAN),--scan --fs 4 --tau 4) --check

# resilience suite alone (fault injection, drain, blue/green, takeover,
# client failover — tests/test_chaos.py and friends)
chaos:
	$(PY) -m pytest tests/ -m chaos -q

# fleet suite alone (rolling restart behind the router under load,
# abort-on-regression legs, router peer retry, shared blacklist, the
# router HA group + elastic autoscaler compound scenario —
# docs/serving.md "Fleet operations", "Router HA & autoscaling")
fleet-chaos:
	$(PY) -m pytest tests/ -m chaos -q -k "fleet or router or rolling or autoscale"

# online-learning loop suite alone (serve→log→train→reload under
# injected faults and a SIGKILL'd trainer — docs/serving.md
# "Continuous learning")
online-chaos:
	$(PY) -m pytest tests/ -m chaos -q -k online

# durability suite alone (WAL append/replay faults, torn replicas, the
# SIGKILL + disk-loss recovery ladder leg — docs/serving.md
# "Durability & recovery")
durability-chaos:
	$(PY) -m pytest tests/ -m chaos -q -k "wal or replica or durab"

# fused-kernel acceptance (ISSUE 13; docs/perf_notes.md "Fused FM
# kernel"): byte-identical trajectories across fused_kernel={off, jnp,
# pallas-if-available} at fs=1 and fs=4, on-device dedup parity vs the
# host np.unique, and the pallas gather/scatter kernels bit-for-bit vs
# the jnp contract (interpret mode off-TPU) — tier-1 time budget
kernel-parity:
	$(PY) -m pytest tests/test_fused.py -q -m 'not slow'

smoke:
	$(PY) bench.py --device-only --steps 2 --batch-size 128 --uniq 256 --capacity 1024 --vdim 4
	$(PY) bench.py --e2e --e2e-rows 2000 --e2e-batch 256 --capacity 4096 --vdim 4
	$(PY) -c "import jax, __graft_entry__; \
	fn, args = __graft_entry__.entry(); \
	jax.block_until_ready(jax.jit(fn)(*args)); \
	__graft_entry__.dryrun_multichip(8); \
	print('entry + dryrun ok')"

ci: lint test hlomap fleet-chaos durability-chaos smoke

# human summary of a run's observability artifacts (docs/observability.md):
#   make obs-report METRICS=run.metrics.jsonl TRACE=run.trace.json
obs-report:
	$(PY) tools/obs_report.py --metrics $(METRICS) $(if $(TRACE),--trace $(TRACE))

# one-time text -> rec2 convert (docs/perf_notes.md "Data formats & the
# streamed fast path"): parallel across cores, zero-copy members out.
#   make convert DATA_IN=criteo.txt DATA_FORMAT=criteo [DATA_OUT=criteo.rec]
convert:
	$(PY) -m difacto_tpu task=convert data_in=$(DATA_IN) \
	  data_format=$(DATA_FORMAT) data_out=$(DATA_OUT) data_out_format=rec

# streamed-regime bench alone (convert + replay + streamed epochs, with
# the per-stage breakdown and the delta vs the newest BENCH_r*.json)
stream-bench:
	$(PY) bench.py --e2e

# fs-sharded capacity-scaling legs alone: table = base*fs rows per fs
# rung in {1,2,4,8}, ex/s + per-device bytes per leg (the MULTICHIP
# metric; docs/perf_notes.md "Mesh-sharded parameter table")
multichip-bench:
	$(PY) bench.py --multichip

# serve→log→train→reload steady state (the online.* BENCH section:
# rows_per_s, train_behind_serve_s_p99, reload_count, label_join_rate)
online-bench:
	$(PY) bench.py --online

# table-capacity levers (ISSUE 19; docs/perf_notes.md "Table capacity"):
# quantized-slot AUC legs at 2x/4x/8x effective capacity vs the fp32
# baseline + cold-tier hit-rate across zipf skews
capacity-bench:
	$(PY) bench.py --capacity

# durability cost/benefit (ISSUE 20; docs/serving.md "Durability &
# recovery"): wal_overhead_pct (target <=5%), recovery_s for the
# checkpoint+replay ladder climb, rpo_batches after a simulated
# mid-window crash (bounded by wal_flush_batches)
durability-bench:
	$(PY) bench.py --durability
