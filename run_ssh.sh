#!/bin/bash
# 2-host ssh cluster run (reference run_ssh.sh equivalent):
# one SPMD process per line of examples/ip_list.txt, working dir rsynced
python launch.py --launcher ssh -H examples/ip_list.txt \
    --sync-dst-dir /tmp/difacto_tpu --max-restarts 1 \
    -- python -m difacto_tpu examples/local.conf "$@"
