#!/usr/bin/env python
"""Multi-process launcher — the dmlc-tracker equivalent, with supervision.

The reference submits scheduler/server/worker processes via dmlc-tracker
(launch.py:32-78, run_local/ssh/yarn.sh) and its DistTracker reassigns a
dead node's work (src/tracker/dist_tracker.h:164-186). The TPU framework is
multi-controller SPMD: every process runs the SAME program; this launcher
starts ``-n`` local processes with the rendezvous env
(DIFACTO_COORDINATOR/NPROCS/RANK -> jax.distributed.initialize, see
difacto_tpu/parallel/multihost.py). On a real TPU pod each host's runtime
(GKE/xpk/ray) sets the equivalent variables instead.

``--max-restarts k`` adds the recovery loop of the dead-host protocol
(difacto_tpu/parallel/fault.py): heartbeat env is exported so workers
detect peer death and abort instead of hanging; when any process fails,
the launcher kills the stragglers, EVICTS one host (local stand-in for
"the dead machine is gone"), and relaunches the survivors — byte-range
input sharding re-partitions the data over them and training resumes from
the last epoch checkpoint (SGDLearner ckpt_interval/auto_resume).

Usage:
    python launch.py -n 2 -- python -m difacto_tpu train.conf k=v ...
    python launch.py -n 2 --max-restarts 1 -- python -m difacto_tpu ...
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time


def _spawn(cmd, n, port, attempt, args):
    procs = []
    for rank in range(n):
        env = dict(os.environ)
        env.update(
            DIFACTO_COORDINATOR=f"127.0.0.1:{port}",
            DIFACTO_NPROCS=str(n),
            DIFACTO_RANK=str(rank),
            DIFACTO_RESTART=str(attempt),
        )
        if args.max_restarts > 0:
            env.update(
                DIFACTO_HB_PORT=str(args.hb_port + 64 * attempt),
                DIFACTO_HB_TIMEOUT=str(args.hb_timeout),
            )
        procs.append(subprocess.Popen(cmd, env=env))
    return procs


def _run_once(cmd, n, port, attempt, args) -> int:
    """0 = all exited cleanly; else the first nonzero rc (stragglers are
    killed: a failed peer leaves them blocked or doomed to abort)."""
    procs = _spawn(cmd, n, port, attempt, args)
    try:
        while True:
            rcs = [p.poll() for p in procs]
            bad = [rc for rc in rcs if rc not in (None, 0)]
            if bad:
                for p in procs:
                    if p.poll() is None:
                        p.kill()
                for p in procs:
                    p.wait()
                return bad[0]
            if all(rc == 0 for rc in rcs):
                return 0
            time.sleep(0.2)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("-n", "--num-processes", type=int, default=1)
    ap.add_argument("--port", type=int, default=7799)
    ap.add_argument("--max-restarts", type=int, default=0,
                    help="recovery attempts after a host failure: evict "
                         "one host, relaunch survivors, resume from the "
                         "last checkpoint (needs ckpt_interval + "
                         "auto_resume in the trained config)")
    ap.add_argument("--hb-port", type=int, default=29800,
                    help="UDP heartbeat base port (rank i binds base+i)")
    ap.add_argument("--hb-timeout", type=float, default=5.0,
                    help="seconds of heartbeat silence before a peer is "
                         "declared dead")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="command to launch (prefix with --)")
    args = ap.parse_args()
    cmd = args.cmd[1:] if args.cmd and args.cmd[0] == "--" else args.cmd
    if not cmd:
        ap.error("no command given")

    n = args.num_processes
    rc = 0
    for attempt in range(args.max_restarts + 1):
        # fresh rendezvous + heartbeat ports per attempt: the previous
        # coordinator socket may linger in TIME_WAIT
        rc = _run_once(cmd, n, args.port + 7 * attempt, attempt, args)
        if rc == 0:
            return 0
        if attempt == args.max_restarts:
            break
        # only host-death exits are recoverable: EXIT_PEER_DEAD (a survivor
        # noticed a dead peer) or signal death (negative rc = the "dead
        # host" itself). A deterministic config/user error would fail
        # identically on every shrinking relaunch — surface it instead.
        try:
            from difacto_tpu.parallel.fault import EXIT_PEER_DEAD
        except ImportError:  # launched from outside the repo
            EXIT_PEER_DEAD = 42
        if rc != EXIT_PEER_DEAD and rc >= 0:
            print(f"[launch] attempt {attempt} failed with non-recovery "
                  f"rc={rc}; not restarting", file=sys.stderr)
            break
        n = max(1, n - 1)
        print(f"[launch] attempt {attempt} failed (rc={rc}); evicting one "
              f"host, relaunching {n} process(es)", file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
