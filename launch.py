#!/usr/bin/env python
"""Multi-process launcher — the dmlc-tracker equivalent, with supervision.

The reference submits scheduler/server/worker processes via dmlc-tracker
(launch.py:32-78, run_local/ssh/yarn.sh) and its DistTracker reassigns a
dead node's work (src/tracker/dist_tracker.h:164-186). The TPU framework is
multi-controller SPMD: every process runs the SAME program; this launcher
starts ``-n`` processes with the rendezvous env
(DIFACTO_COORDINATOR/NPROCS/RANK -> jax.distributed.initialize, see
difacto_tpu/parallel/multihost.py).

Launch modes (--launcher, the dmlc-tracker cluster types):
  local  processes on this machine (default);
  ssh    one process per line of ``-H hostfile`` (the run_ssh.sh path,
         /root/reference/run_ssh.sh:1, example/ip_list.txt): the
         rendezvous coordinator is the first host, env rides the remote
         command line, and ``--sync-dst-dir`` rsyncs the working dir to
         every host first (dmlc-tracker's sync behavior). On managed
         clusters (k8s/xpk/slurm, the yarn equivalents) the scheduler
         sets the DIFACTO_* variables itself — no launcher needed.

``--max-restarts k`` adds the recovery loop of the dead-host protocol
(difacto_tpu/parallel/fault.py): heartbeat env is exported so workers
detect peer death and abort instead of hanging; when a process dies by
signal or aborts with EXIT_PEER_DEAD, the launcher kills the stragglers,
EVICTS one host, and relaunches the survivors — byte-range input sharding
re-partitions the data over them and training resumes from the last epoch
checkpoint (SGDLearner ckpt_interval/auto_resume).

Usage:
    python launch.py -n 2 -- python -m difacto_tpu train.conf k=v ...
    python launch.py -n 2 --max-restarts 1 -- python -m difacto_tpu ...
    python launch.py -H hosts.txt --launcher ssh --sync-dst-dir /tmp/job \\
        -- python -m difacto_tpu train.conf
"""

from __future__ import annotations

import argparse
import os
import shlex
import subprocess
import sys
import time


def _read_hostfile(path: str) -> list:
    hosts = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                hosts.append(line.split()[0])
    if not hosts:
        raise SystemExit(f"hostfile {path} lists no hosts")
    return hosts


def _sync(hosts, dst, args) -> None:
    """rsync the working directory to each ACTIVE host, concurrently
    (dmlc-tracker ssh launcher behavior, reference launch.py:41-44
    sync_dst_dir)."""
    src = os.getcwd() + "/"
    procs = [subprocess.Popen(
        args.rsync_cmd.split() + ["-az", "--delete", src, f"{h}:{dst}/"])
        for h in hosts]
    for h, p in zip(hosts, procs):
        if p.wait() != 0:
            raise SystemExit(f"rsync to {h} failed")


def _rank_env(rank, n, hosts, port, attempt, args) -> dict:
    coord = (hosts[0] if hosts else "127.0.0.1")
    env = {
        "DIFACTO_COORDINATOR": f"{coord}:{port}",
        "DIFACTO_NPROCS": str(n),
        "DIFACTO_RANK": str(rank),
        "DIFACTO_RESTART": str(attempt),
    }
    if args.max_restarts > 0:
        env.update(
            DIFACTO_HB_PORT=str(args.hb_port + 64 * attempt),
            DIFACTO_HB_TIMEOUT=str(args.hb_timeout),
        )
        if hosts:
            env["DIFACTO_HB_PEERS"] = ",".join(hosts)
    return env


def _spawn(cmd, n, hosts, port, attempt, args):
    procs = []
    for rank in range(n):
        extra = _rank_env(rank, n, hosts, port, attempt, args)
        if hosts:
            # env must ride the remote command line: ssh does not forward
            # the local environment
            envs = " ".join(f"{k}={shlex.quote(v)}"
                            for k, v in extra.items())
            wd = args.sync_dst_dir or "."
            remote = (f"cd {shlex.quote(wd)} && env {envs} "
                      + " ".join(shlex.quote(c) for c in cmd))
            full = args.ssh_cmd.split() + [hosts[rank], remote]
            procs.append(subprocess.Popen(full))
        else:
            env = dict(os.environ)
            env.update(extra)
            procs.append(subprocess.Popen(cmd, env=env))
    return procs


def _is_signal_death(rc: int, ssh: bool) -> bool:
    """Negative rc = local signal death. The >128 band (shell convention
    128+signo; 255 = ssh could not reach the host) only means signal
    death when the status was relayed through ssh — a LOCAL worker
    exiting 255 is a deterministic error, not a dead host."""
    return rc < 0 or (ssh and rc > 128)


def _peer_dead_rank(rc: int) -> int:
    """Dead rank encoded by fault.exit_code_for (101..127), else -1."""
    return rc - 100 if 100 < rc < 128 else -1


def _run_once(cmd, n, hosts, port, attempt, args):
    """(rc, failed_rank): rc 0 = all exited cleanly; else the first
    nonzero rc and its rank (stragglers are killed: a failed peer leaves
    them blocked or doomed to abort)."""
    procs = _spawn(cmd, n, hosts, port, attempt, args)
    try:
        while True:
            rcs = [p.poll() for p in procs]
            bad = [(rank, rc) for rank, rc in enumerate(rcs)
                   if rc not in (None, 0)]
            if bad:
                for p in procs:
                    if p.poll() is None:
                        p.kill()
                for p in procs:
                    p.wait()
                # eviction preference: a directly-observed signal death
                # (the dead host itself), else a survivor's encoded
                # dead-rank report, else whatever failed first
                ssh = bool(hosts)

                def prio(t):
                    if _is_signal_death(t[1], ssh):
                        return 0
                    if _peer_dead_rank(t[1]) >= 0:
                        return 1
                    return 2
                bad.sort(key=prio)
                return bad[0][1], bad[0][0]
            if all(rc == 0 for rc in rcs):
                return 0, -1
            time.sleep(0.2)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("-n", "--num-processes", type=int, default=0,
                    help="process count (default: 1, or the hostfile "
                         "length with -H)")
    ap.add_argument("-H", "--hostfile", default="",
                    help="one host per line (# comments ok); used by the "
                         "ssh launcher, reference example/ip_list.txt")
    ap.add_argument("--launcher", choices=("local", "ssh"),
                    default="local")
    ap.add_argument("--sync-dst-dir", default="",
                    help="rsync the current directory to this path on "
                         "every host before launching (ssh mode)")
    ap.add_argument("--ssh-cmd", default="ssh",
                    help="ssh executable + base flags (override for "
                         "tests or for gcloud compute ssh wrappers)")
    ap.add_argument("--rsync-cmd", default="rsync")
    ap.add_argument("--port", type=int, default=7799)
    ap.add_argument("--max-restarts", type=int, default=0,
                    help="recovery attempts after a host failure: evict "
                         "one host, relaunch survivors, resume from the "
                         "last checkpoint (needs ckpt_interval + "
                         "auto_resume in the trained config)")
    ap.add_argument("--hb-port", type=int, default=29800,
                    help="UDP heartbeat base port (rank i binds base+i)")
    ap.add_argument("--hb-timeout", type=float, default=5.0,
                    help="seconds of heartbeat silence before a peer is "
                         "declared dead")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="command to launch (prefix with --)")
    args = ap.parse_args()
    cmd = args.cmd[1:] if args.cmd and args.cmd[0] == "--" else args.cmd
    if not cmd:
        ap.error("no command given")

    hosts = []
    if args.launcher == "ssh":
        if not args.hostfile:
            ap.error("--launcher ssh requires -H/--hostfile")
        hosts = _read_hostfile(args.hostfile)
        if args.sync_dst_dir:
            _sync(hosts, args.sync_dst_dir, args)
    n = args.num_processes or (len(hosts) if hosts else 1)
    if hosts and n > len(hosts):
        ap.error(f"-n {n} exceeds the {len(hosts)} hostfile entries")

    rc = 0
    cur_hosts = hosts[:n]
    for attempt in range(args.max_restarts + 1):
        # fresh rendezvous + heartbeat ports per attempt: the previous
        # coordinator socket may linger in TIME_WAIT
        rc, bad_rank = _run_once(cmd, n, cur_hosts, args.port + 7 * attempt,
                                 attempt, args)
        if rc == 0:
            return 0
        if attempt == args.max_restarts:
            break
        # only host-death exits are recoverable: EXIT_PEER_DEAD (a survivor
        # noticed a dead peer) or signal death (negative rc = the "dead
        # host" itself). A deterministic config/user error would fail
        # identically on every shrinking relaunch — surface it instead.
        try:
            from difacto_tpu.parallel.fault import EXIT_PEER_DEAD
        except ImportError:  # launched from outside the repo
            EXIT_PEER_DEAD = 42
        ssh = bool(cur_hosts)
        recoverable = (rc == EXIT_PEER_DEAD or _peer_dead_rank(rc) >= 0
                       or _is_signal_death(rc, ssh))
        if not recoverable:
            print(f"[launch] attempt {attempt} failed with non-recovery "
                  f"rc={rc}; not restarting", file=sys.stderr)
            break
        if cur_hosts and len(cur_hosts) == 1:
            print("[launch] no hosts left to evict; giving up",
                  file=sys.stderr)
            break
        n = max(1, n - 1)
        if cur_hosts:
            # whom to evict: the signal-dead rank if the launcher saw it
            # die, else the dead rank a survivor reported via its encoded
            # exit code, else fall back to the last host
            if _is_signal_death(rc, ssh) and bad_rank >= 0:
                victim = bad_rank
            elif 0 <= _peer_dead_rank(rc) < len(cur_hosts):
                victim = _peer_dead_rank(rc)
            else:
                victim = len(cur_hosts) - 1
            evicted = cur_hosts.pop(victim)
            # ssh cannot kill remote stragglers; give orphans of the
            # failed attempt one heartbeat timeout to notice their dead
            # peers and self-abort before the relaunch races them
            time.sleep(args.hb_timeout)
            print(f"[launch] attempt {attempt} failed (rc={rc}); evicting "
                  f"{evicted}, relaunching on {cur_hosts}", file=sys.stderr)
        else:
            print(f"[launch] attempt {attempt} failed (rc={rc}); evicting "
                  f"one host, relaunching {n} process(es)", file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
