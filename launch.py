#!/usr/bin/env python
"""Multi-process launcher — the dmlc-tracker equivalent.

The reference submits scheduler/server/worker processes via dmlc-tracker
(launch.py:32-78, run_local/ssh/yarn.sh). The TPU framework is
multi-controller SPMD: every process runs the SAME program; this launcher
starts ``-n`` local processes with the rendezvous env
(DIFACTO_COORDINATOR/NPROCS/RANK -> jax.distributed.initialize, see
difacto_tpu/parallel/multihost.py). On a real TPU pod each host's runtime
(GKE/xpk/ray) sets the equivalent variables instead.

Usage:
    python launch.py -n 2 -- python -m difacto_tpu train.conf k=v ...
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("-n", "--num-processes", type=int, default=1)
    ap.add_argument("--port", type=int, default=7799)
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="command to launch (prefix with --)")
    args = ap.parse_args()
    cmd = args.cmd[1:] if args.cmd and args.cmd[0] == "--" else args.cmd
    if not cmd:
        ap.error("no command given")

    procs = []
    for rank in range(args.num_processes):
        env = dict(os.environ)
        env.update(
            DIFACTO_COORDINATOR=f"127.0.0.1:{args.port}",
            DIFACTO_NPROCS=str(args.num_processes),
            DIFACTO_RANK=str(rank),
        )
        procs.append(subprocess.Popen(cmd, env=env))
    rc = 0
    for p in procs:
        rc |= p.wait()
    return rc


if __name__ == "__main__":
    sys.exit(main())
