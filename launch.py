#!/usr/bin/env python
"""Multi-process launcher — the dmlc-tracker equivalent, with supervision.

The reference submits scheduler/server/worker processes via dmlc-tracker
(launch.py:32-78, run_local/ssh/yarn.sh) and its DistTracker reassigns a
dead node's work (src/tracker/dist_tracker.h:164-186). The TPU framework is
multi-controller SPMD: every process runs the SAME program; this launcher
starts ``-n`` processes with the rendezvous env
(DIFACTO_COORDINATOR/NPROCS/RANK -> jax.distributed.initialize, see
difacto_tpu/parallel/multihost.py).

Launch modes (--launcher, the dmlc-tracker cluster types,
reference launch.py:32-78):
  local  processes on this machine (default);
  ssh    one process per line of ``-H hostfile`` (the run_ssh.sh path,
         /root/reference/run_ssh.sh:1, example/ip_list.txt): the
         rendezvous coordinator is the first host, env rides the remote
         command line, and ``--sync-dst-dir`` rsyncs the working dir to
         every host first (dmlc-tracker's sync behavior);
  mpi    one ``mpirun`` over the allocation; each MPI rank runs this
         script's ``shim`` mode, which maps the MPI rank env
         (OMPI_COMM_WORLD_RANK / PMI_RANK / PMIX_RANK) to DIFACTO_RANK
         and resolves the coordinator through a shared rendezvous dir;
  sge    a ``qsub`` array job (-t 1-N, $SGE_TASK_ID-1 = rank) whose
         tasks run the shim; the launcher polls per-rank rc files on
         the shared filesystem until the job completes;
  yarn   a YARN distributed-shell submission (-num_containers N);
         containers carry no rank, so the shim atomically CLAIMS one
         via O_EXCL files in the rendezvous dir.

The cluster modes share one protocol (the dmlc-tracker equivalent): every
task runs ``launch.py shim``, which (1) determines its rank, (2) writes
its hostname to ``<rendezvous-dir>/host-<rank>``, (3) polls for
``host-0`` (rank 0 must be the jax.distributed coordinator), (4) execs
the training command with the DIFACTO_* rendezvous env, and (5) records
its exit code in ``rc-<rank>``. The rendezvous dir must be on a
filesystem all tasks share (SGE/YARN clusters have one; MPI allocations
usually share $HOME); each submission works in its own ``run-*`` subdir,
so the recovery unit for cluster modes is a whole resubmission (fresh
subdir + ckpt auto_resume), not a per-task rerun — a rerun inside one
submission would meet the first attempt's claim/rc files. Schedulers
that pre-assign stable host lists (k8s/xpk/slurm) can skip the launcher
entirely and set the DIFACTO_* variables themselves.

``--max-restarts k`` adds the recovery loop of the dead-host protocol
(difacto_tpu/parallel/fault.py): heartbeat env is exported so workers
detect peer death and abort instead of hanging; when a process dies by
signal or aborts with EXIT_PEER_DEAD, the launcher kills the stragglers,
EVICTS one host, and relaunches the survivors — byte-range input sharding
re-partitions the data over them and training resumes from the last epoch
checkpoint (SGDLearner ckpt_interval/auto_resume). A single-host job
run under the launcher with ``wal_flush_batches``/``replica_peers`` set
gets the tighter story for free: the relaunch's same auto_resume path
climbs the durability ladder (local checkpoint → peer fetch → WAL
replay; docs/serving.md "Durability & recovery") — the launcher itself
needs no new flags.

Usage:
    python launch.py -n 2 -- python -m difacto_tpu train.conf k=v ...
    python launch.py -n 2 --max-restarts 1 -- python -m difacto_tpu ...
    python launch.py -H hosts.txt --launcher ssh --sync-dst-dir /tmp/job \\
        -- python -m difacto_tpu train.conf
"""

from __future__ import annotations

import argparse
import os
import random
import shlex
import subprocess
import sys
import time

# relaunch backoff cap: repeated eviction-relaunch cycles wait at most
# this long (plus jitter) between attempts
RELAUNCH_BACKOFF_CAP_S = 60.0


def _obs_inc(name: str, help_: str = "") -> None:
    """Count a supervision event into the obs registry (difacto_tpu/obs)
    when the repo is importable — the launcher also runs standalone on
    bare cluster hosts, where this is a silent no-op."""
    try:
        from difacto_tpu.obs import counter
    except ImportError:  # pragma: no cover - launched outside the repo
        return
    counter(name, help_).inc()


def _relaunch_delay(attempt: int, hb_timeout: float,
                    rng: random.Random = random) -> float:
    """Seconds to wait before relaunch ``attempt`` + 1: exponential in
    the attempt number with full jitter, floored at one heartbeat
    timeout (ssh can't kill remote stragglers — orphans of the failed
    attempt need a full hb window to notice their dead peers and
    self-abort before the relaunch races them) and capped so a long
    eviction cascade doesn't stall recovery. The jitter is the point:
    a fleet of restarting launchers must not stampede the coordinator
    port in lockstep."""
    base = min(hb_timeout * (2 ** attempt), RELAUNCH_BACKOFF_CAP_S)
    return max(hb_timeout, base * (0.5 + rng.random()))


# public alias: the router-group supervisor (tools/fleet.py routers)
# relaunches dead group members on the same schedule the multi-host
# launcher uses — one backoff policy for the whole system
relaunch_delay = _relaunch_delay


def _read_hostfile(path: str) -> list:
    hosts = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                hosts.append(line.split()[0])
    if not hosts:
        raise SystemExit(f"hostfile {path} lists no hosts")
    return hosts


def _sync(hosts, dst, args) -> None:
    """rsync the working directory to each ACTIVE host, concurrently
    (dmlc-tracker ssh launcher behavior, reference launch.py:41-44
    sync_dst_dir)."""
    src = os.getcwd() + "/"
    procs = [subprocess.Popen(
        args.rsync_cmd.split() + ["-az", "--delete", src, f"{h}:{dst}/"])
        for h in hosts]
    for h, p in zip(hosts, procs):
        if p.wait() != 0:
            raise SystemExit(f"rsync to {h} failed")


def _rank_env(rank, n, hosts, port, attempt, args) -> dict:
    coord = (hosts[0] if hosts else "127.0.0.1")
    env = {
        "DIFACTO_COORDINATOR": f"{coord}:{port}",
        "DIFACTO_NPROCS": str(n),
        "DIFACTO_RANK": str(rank),
        "DIFACTO_RESTART": str(attempt),
    }
    if args.bounded_delay >= 0:
        # cluster-wide τ: every worker reads DIFACTO_BOUNDED_DELAY when
        # the trained config leaves bounded_delay unset (-1)
        env["DIFACTO_BOUNDED_DELAY"] = str(args.bounded_delay)
    if args.max_restarts > 0:
        env.update(
            DIFACTO_HB_PORT=str(args.hb_port + 64 * attempt),
            DIFACTO_HB_TIMEOUT=str(args.hb_timeout),
        )
        if hosts:
            env["DIFACTO_HB_PEERS"] = ",".join(hosts)
    return env


def _spawn(cmd, n, hosts, port, attempt, args):
    procs = []
    for rank in range(n):
        extra = _rank_env(rank, n, hosts, port, attempt, args)
        if hosts:
            # env must ride the remote command line: ssh does not forward
            # the local environment
            envs = " ".join(f"{k}={shlex.quote(v)}"
                            for k, v in extra.items())
            wd = args.sync_dst_dir or "."
            remote = (f"cd {shlex.quote(wd)} && env {envs} "
                      + " ".join(shlex.quote(c) for c in cmd))
            full = args.ssh_cmd.split() + [hosts[rank], remote]
            procs.append(subprocess.Popen(full))
        else:
            env = dict(os.environ)
            env.update(extra)
            procs.append(subprocess.Popen(cmd, env=env))
    return procs


def _is_signal_death(rc: int, ssh: bool) -> bool:
    """Negative rc = local signal death. The >128 band (shell convention
    128+signo; 255 = ssh could not reach the host) only means signal
    death when the status was relayed through ssh — a LOCAL worker
    exiting 255 is a deterministic error, not a dead host."""
    return rc < 0 or (ssh and rc > 128)


def _peer_dead_rank(rc: int) -> int:
    """Dead rank encoded by fault.exit_code_for (101..127), else -1."""
    return rc - 100 if 100 < rc < 128 else -1


def _run_once(cmd, n, hosts, port, attempt, args):
    """(rc, failed_rank): rc 0 = all exited cleanly; else the first
    nonzero rc and its rank (stragglers are killed: a failed peer leaves
    them blocked or doomed to abort)."""
    procs = _spawn(cmd, n, hosts, port, attempt, args)
    try:
        while True:
            rcs = [p.poll() for p in procs]
            bad = [(rank, rc) for rank, rc in enumerate(rcs)
                   if rc not in (None, 0)]
            if bad:
                for p in procs:
                    if p.poll() is None:
                        p.kill()
                for p in procs:
                    p.wait()
                # eviction preference: a directly-observed signal death
                # (the dead host itself), else a survivor's encoded
                # dead-rank report, else whatever failed first
                ssh = bool(hosts)

                def prio(t):
                    if _is_signal_death(t[1], ssh):
                        return 0
                    if _peer_dead_rank(t[1]) >= 0:
                        return 1
                    return 2
                bad.sort(key=prio)
                return bad[0][1], bad[0][0]
            if all(rc == 0 for rc in rcs):
                return 0, -1
            time.sleep(0.2)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


# --------------------------------------------------------------- cluster
# mpi/sge/yarn support: shared-filesystem rendezvous + rank shim.

_MPI_RANK_VARS = ("OMPI_COMM_WORLD_RANK", "PMIX_RANK", "PMI_RANK",
                  "SLURM_PROCID")


def _claim_rank(rdv: str, n: int) -> int:
    """Atomically claim the lowest free rank via O_EXCL claim files —
    for schedulers whose tasks carry no rank of their own (yarn
    distributed-shell containers)."""
    import socket
    for rank in range(n):
        try:
            fd = os.open(os.path.join(rdv, f"claim-{rank}"),
                         os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.write(fd, socket.gethostname().encode())
            os.close(fd)
            return rank
        except FileExistsError:
            continue
    raise SystemExit(f"all {n} ranks already claimed in {rdv}")


def _write_rc(rdv: str, rank: int, rc: int) -> None:
    with open(os.path.join(rdv, f"rc-{rank}.tmp"), "w") as f:
        f.write(str(rc))
    os.replace(os.path.join(rdv, f"rc-{rank}.tmp"),
               os.path.join(rdv, f"rc-{rank}"))


def run_shim(args, cmd) -> int:
    """Per-task half of the cluster protocol (see module docstring)."""
    import socket
    rdv = args.rendezvous_dir
    os.makedirs(rdv, exist_ok=True)
    if args.rank >= 0:
        rank = args.rank
    else:
        rank = next((int(os.environ[v]) for v in _MPI_RANK_VARS
                     if v in os.environ), -1)
        if rank < 0:
            rank = _claim_rank(rdv, args.num_processes)
    # from here the rank is known: ANY exit must leave an rc file, or the
    # launcher's rc-file wait (default: no deadline) would spin forever
    # on a shim that failed before running the command
    try:
        rc = _run_shim_ranked(args, cmd, rdv, rank, socket.gethostname())
    except BaseException:
        _write_rc(rdv, rank, 1)
        raise
    _write_rc(rdv, rank, rc)
    return rc


def _run_shim_ranked(args, cmd, rdv: str, rank: int, hostname: str) -> int:
    host_f = os.path.join(rdv, f"host-{rank}")
    with open(host_f + ".tmp", "w") as f:
        f.write(hostname)
    os.replace(host_f + ".tmp", host_f)
    # rank 0 IS the jax.distributed coordinator; the full host list also
    # feeds the UDP heartbeat mesh (fault.py) so a dead container aborts
    # its peers fast instead of leaving them blocked in a collective —
    # so every task waits for ALL host files (they appear during the
    # same startup window as host-0)
    deadline = time.monotonic() + args.rendezvous_timeout
    hosts = [None] * args.num_processes
    while any(h is None for h in hosts):
        for r in range(args.num_processes):
            if hosts[r] is None:
                p = os.path.join(rdv, f"host-{r}")
                if os.path.exists(p):
                    with open(p) as f:
                        hosts[r] = f.read().strip()
        if any(h is None for h in hosts):
            if time.monotonic() > deadline:
                missing = [r for r, h in enumerate(hosts) if h is None]
                raise SystemExit(
                    f"rendezvous timeout: no host file for rank(s) "
                    f"{missing} in {rdv}")
            time.sleep(0.2)
    env = dict(os.environ)
    env.update({
        "DIFACTO_COORDINATOR": f"{hosts[0]}:{args.port}",
        "DIFACTO_NPROCS": str(args.num_processes),
        "DIFACTO_RANK": str(rank),
        "DIFACTO_HB_PORT": str(args.hb_port),
        "DIFACTO_HB_TIMEOUT": str(args.hb_timeout),
        "DIFACTO_HB_PEERS": ",".join(hosts),
    })
    if args.bounded_delay >= 0:
        env["DIFACTO_BOUNDED_DELAY"] = str(args.bounded_delay)
    return subprocess.call(cmd, env=env)


def _shim_cmd(args, cmd, rank_expr=None) -> str:
    """Shell line that runs this script in shim mode on a cluster task."""
    base = [sys.executable if args.local_python else "python",
            os.path.abspath(__file__), "shim",
            "--rendezvous-dir", args.rendezvous_dir,
            "--port", str(args.port),
            "-n", str(args.num_processes),
            "--rendezvous-timeout", str(args.rendezvous_timeout),
            "--hb-port", str(args.hb_port),
            "--hb-timeout", str(args.hb_timeout),
            "--bounded-delay", str(args.bounded_delay)]
    line = " ".join(shlex.quote(c) for c in base)
    if rank_expr is not None:
        line += f" --rank {rank_expr}"
    return line + " -- " + " ".join(shlex.quote(c) for c in cmd)


def _wait_cluster_rcs(rdv: str, n: int, timeout: float) -> int:
    """Poll the shims' rc files; first nonzero rc wins (matching
    _run_once's semantics). ``timeout`` bounds the WHOLE job
    (--job-timeout; 0 = wait forever) — it is deliberately separate from
    --rendezvous-timeout, which bounds only task startup: a training run
    outlives any sane rendezvous deadline."""
    deadline = time.monotonic() + timeout if timeout > 0 else None
    seen = {}
    while len(seen) < n:
        for rank in range(n):
            if rank in seen:
                continue
            p = os.path.join(rdv, f"rc-{rank}")
            if os.path.exists(p):
                with open(p) as f:
                    seen[rank] = int(f.read().strip() or "1")
        if len(seen) < n and deadline is not None and time.monotonic() > deadline:
            missing = [r for r in range(n) if r not in seen]
            print(f"[launch] timeout waiting for rank(s) {missing} in {rdv}",
                  file=sys.stderr)
            return 1
        time.sleep(0.2)
    bad = [rc for rc in seen.values() if rc != 0]
    return bad[0] if bad else 0


def run_cluster(args, cmd) -> int:
    """Submit through mpirun / qsub / yarn and wait on the rendezvous
    dir's rc files (the dmlc-tracker submit equivalents,
    reference launch.py:32-78, run_yarn.sh:3)."""
    n = args.num_processes or 1
    args.num_processes = n
    if args.max_restarts > 0:
        # resubmission is the scheduler's job in these modes (qsub/yarn
        # retry policies; mpirun has none) — failing fast beats silently
        # running without the recovery the user asked for. The shims DO
        # start the heartbeat mesh, so peer death still aborts fast.
        # NOTE the retry unit is the WHOLE job (a fresh launch.py
        # submission gets a fresh run-* rendezvous subdir): per-task
        # reruns inside one submission would meet the first attempt's
        # claim/rc files and be reported as that attempt's result.
        raise SystemExit(
            f"--max-restarts is not supported with --launcher "
            f"{args.launcher}: have the scheduler retry the WHOLE "
            "submission (each gets a fresh rendezvous subdir) with "
            "ckpt_interval/auto_resume in the trained config")
    if not args.rendezvous_dir:
        raise SystemExit(f"--launcher {args.launcher} requires "
                         "--rendezvous-dir on a shared filesystem")
    # unique per-submission subdir: reusing a rendezvous dir would hand
    # new tasks the PREVIOUS run's claim/host/rc files (ranks 'already
    # claimed', stale coordinator, rc collection reporting the old
    # run's result). The submit time + pid make the path unique; the
    # shims receive it fully resolved on their command line.
    args.rendezvous_dir = os.path.join(
        args.rendezvous_dir,
        f"run-{int(time.time())}-{os.getpid()}")  # lint: ok(wall-clock) stamp
    rdv = args.rendezvous_dir
    os.makedirs(rdv, exist_ok=False)
    if args.launcher == "mpi":
        # one mpirun across the allocation; ranks come from the MPI env
        full = (args.mpirun_cmd.split() + ["-np", str(n)]
                + ["/bin/sh", "-c", _shim_cmd(args, cmd)])
        rc = subprocess.call(full)
        if rc != 0:
            return rc
        return _wait_cluster_rcs(rdv, n, args.job_timeout)
    if args.launcher == "sge":
        # array job: $SGE_TASK_ID is 1-based
        script = os.path.join(rdv, "job.sh")
        with open(script, "w") as f:
            f.write("#!/bin/sh\n"
                    f"#$ -t 1-{n}\n#$ -cwd\n#$ -S /bin/sh\n"
                    + _shim_cmd(args, cmd,
                                rank_expr="$((SGE_TASK_ID-1))") + "\n")
        os.chmod(script, 0o755)
        rc = subprocess.call(args.qsub_cmd.split() + [script])
        if rc != 0:
            return rc
        return _wait_cluster_rcs(rdv, n, args.job_timeout)
    # yarn distributed shell: containers carry no rank -> shims claim one
    full = (args.yarn_cmd.split()
            + ["-num_containers", str(n),
               "-shell_command", _shim_cmd(args, cmd)])
    rc = subprocess.call(full)
    if rc != 0:
        return rc
    return _wait_cluster_rcs(rdv, n, args.job_timeout)


def main() -> int:
    if len(sys.argv) > 1 and sys.argv[1] == "shim":
        sp = argparse.ArgumentParser(prog="launch.py shim")
        sp.add_argument("--rendezvous-dir", required=True)
        sp.add_argument("--port", type=int, default=7799)
        sp.add_argument("-n", "--num-processes", type=int, required=True)
        sp.add_argument("--rank", type=int, default=-1)
        sp.add_argument("--rendezvous-timeout", type=float, default=300.0)
        sp.add_argument("--hb-port", type=int, default=29800)
        sp.add_argument("--hb-timeout", type=float, default=5.0)
        sp.add_argument("--bounded-delay", type=int, default=-1)
        sp.add_argument("cmd", nargs=argparse.REMAINDER)
        sa = sp.parse_args(sys.argv[2:])
        scmd = sa.cmd[1:] if sa.cmd and sa.cmd[0] == "--" else sa.cmd
        if not scmd:
            sp.error("no command given")
        return run_shim(sa, scmd)

    ap = argparse.ArgumentParser()
    ap.add_argument("-n", "--num-processes", type=int, default=0,
                    help="process count (default: 1, or the hostfile "
                         "length with -H)")
    ap.add_argument("-H", "--hostfile", default="",
                    help="one host per line (# comments ok); used by the "
                         "ssh launcher, reference example/ip_list.txt")
    ap.add_argument("--launcher",
                    choices=("local", "ssh", "mpi", "sge", "yarn"),
                    default="local")
    ap.add_argument("--rendezvous-dir", default="",
                    help="shared-filesystem dir for the cluster modes' "
                         "host/rank rendezvous (mpi/sge/yarn)")
    ap.add_argument("--rendezvous-timeout", type=float, default=300.0,
                    help="seconds each cluster task waits for its peers' "
                         "host files at STARTUP (mpi/sge/yarn)")
    ap.add_argument("--job-timeout", type=float, default=0.0,
                    help="seconds to wait for the WHOLE cluster job's rc "
                         "files after submission; 0 (default) waits "
                         "forever — training runs outlive any sane "
                         "rendezvous deadline, so this is a separate "
                         "knob")
    ap.add_argument("--mpirun-cmd", default="mpirun",
                    help="mpirun executable + base flags (mpi mode)")
    ap.add_argument("--qsub-cmd", default="qsub",
                    help="qsub executable + base flags (sge mode)")
    ap.add_argument("--yarn-cmd",
                    default="yarn org.apache.hadoop.yarn.applications."
                            "distributedshell.Client",
                    help="yarn distributed-shell client + base flags "
                         "(yarn mode; point -jar etc. here)")
    ap.add_argument("--local-python", action="store_true",
                    help="cluster tasks run this exact interpreter "
                         "(sys.executable) instead of 'python' from the "
                         "remote PATH — for single-machine tests")
    ap.add_argument("--sync-dst-dir", default="",
                    help="rsync the current directory to this path on "
                         "every host before launching (ssh mode)")
    ap.add_argument("--ssh-cmd", default="ssh",
                    help="ssh executable + base flags (override for "
                         "tests or for gcloud compute ssh wrappers)")
    ap.add_argument("--rsync-cmd", default="rsync")
    ap.add_argument("--port", type=int, default=7799)
    ap.add_argument("--max-restarts", type=int, default=0,
                    help="recovery attempts after a host failure: evict "
                         "one host, relaunch survivors, resume from the "
                         "last checkpoint (needs ckpt_interval + "
                         "auto_resume in the trained config)")
    ap.add_argument("--bounded-delay", type=int, default=-1,
                    help="τ: batches of bounded-delay staleness the "
                         "windowed SPMD exchange may pipeline ahead "
                         "(exported as DIFACTO_BOUNDED_DELAY to every "
                         "rank; 0 = fully synchronous, -1 = leave the "
                         "trained config's bounded_delay in charge)")
    ap.add_argument("--hb-port", type=int, default=29800,
                    help="UDP heartbeat base port (rank i binds base+i)")
    ap.add_argument("--hb-timeout", type=float, default=5.0,
                    help="seconds of heartbeat silence before a peer is "
                         "declared dead")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="command to launch (prefix with --)")
    args = ap.parse_args()
    cmd = args.cmd[1:] if args.cmd and args.cmd[0] == "--" else args.cmd
    if not cmd:
        ap.error("no command given")

    if args.launcher in ("mpi", "sge", "yarn"):
        return run_cluster(args, cmd)

    hosts = []
    if args.launcher == "ssh":
        if not args.hostfile:
            ap.error("--launcher ssh requires -H/--hostfile")
        hosts = _read_hostfile(args.hostfile)
        if args.sync_dst_dir:
            _sync(hosts, args.sync_dst_dir, args)
    n = args.num_processes or (len(hosts) if hosts else 1)
    if hosts and n > len(hosts):
        ap.error(f"-n {n} exceeds the {len(hosts)} hostfile entries")

    rc = 0
    cur_hosts = hosts[:n]
    for attempt in range(args.max_restarts + 1):
        # fresh rendezvous + heartbeat ports per attempt: the previous
        # coordinator socket may linger in TIME_WAIT
        rc, bad_rank = _run_once(cmd, n, cur_hosts, args.port + 7 * attempt,
                                 attempt, args)
        if rc == 0:
            return 0
        if attempt == args.max_restarts:
            break
        # only host-death exits are recoverable: EXIT_PEER_DEAD (a survivor
        # noticed a dead peer) or signal death (negative rc = the "dead
        # host" itself). A deterministic config/user error would fail
        # identically on every shrinking relaunch — surface it instead.
        try:
            from difacto_tpu.parallel.fault import EXIT_PEER_DEAD
        except ImportError:  # launched from outside the repo
            EXIT_PEER_DEAD = 42
        ssh = bool(cur_hosts)
        recoverable = (rc == EXIT_PEER_DEAD or _peer_dead_rank(rc) >= 0
                       or _is_signal_death(rc, ssh))
        if not recoverable:
            print(f"[launch] attempt {attempt} failed with non-recovery "
                  f"rc={rc}; not restarting", file=sys.stderr)
            break
        if cur_hosts and len(cur_hosts) == 1:
            print("[launch] no hosts left to evict; giving up",
                  file=sys.stderr)
            break
        n = max(1, n - 1)
        if cur_hosts:
            # whom to evict: the signal-dead rank if the launcher saw it
            # die, else the dead rank a survivor reported via its encoded
            # exit code, else fall back to the last host
            if _is_signal_death(rc, ssh) and bad_rank >= 0:
                victim = bad_rank
            elif 0 <= _peer_dead_rank(rc) < len(cur_hosts):
                victim = _peer_dead_rank(rc)
            else:
                victim = len(cur_hosts) - 1
            evicted = cur_hosts.pop(victim)
            _obs_inc("launch_evictions_total",
                     "hosts evicted after a detected death")
            # exponential backoff + jitter between relaunches (floored
            # at one heartbeat timeout so ssh orphans self-abort first)
            time.sleep(_relaunch_delay(attempt, args.hb_timeout))
            print(f"[launch] attempt {attempt} failed (rc={rc}); evicting "
                  f"{evicted}, relaunching on {cur_hosts}", file=sys.stderr)
        else:
            _obs_inc("launch_evictions_total",
                     "hosts evicted after a detected death")
            print(f"[launch] attempt {attempt} failed (rc={rc}); evicting "
                  f"one host, relaunching {n} process(es)", file=sys.stderr)
        _obs_inc("launch_relaunches_total",
                 "survivor relaunch attempts after an eviction")
    return rc


if __name__ == "__main__":
    sys.exit(main())
